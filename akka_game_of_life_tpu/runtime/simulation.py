"""Standalone simulation driver: config → board → stepper → observer.

This is the single-process equivalent of the reference's whole cluster — the
coordinator loop that ``BoardCreator`` implements with timers and message
fan-out (``BoardCreator.scala:105-116``) becomes a host loop around a jitted
(and, multi-device, sharded) step function.  Pacing is free-running by
default; set ``tick_s`` to reproduce the reference's fixed wall-clock cadence.

Crash recovery is checkpoint + deterministic replay: a crash (injected by the
chaos scheduler, or a real kill + re-launch) discards in-memory state, the
latest checkpoint is restored, and the missed epochs are recomputed — the
same trajectory, because the update is deterministic.  This is the TPU-native
version of the reference's replay-from-neighbor-histories recovery
(SURVEY.md §3.3) without its unbounded memory."""

from __future__ import annotations

import contextlib
import signal
import threading
import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.models import get_model
from akka_game_of_life_tpu.ops import bitpack, bitpack_gen
from akka_game_of_life_tpu.ops.rules import resolve_rule
from akka_game_of_life_tpu.parallel import (
    distributed as dist,
    make_grid_mesh,
    shard_board,
    sharded_step_fn,
    validate_tile_shape,
)
from akka_game_of_life_tpu.parallel.packed_halo2d import (
    shard_packed2d,
    sharded_packed2d_step_fn,
    word_halo_width,
)
from akka_game_of_life_tpu.obs import EventLog, MetricsDumper, get_registry
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.runtime import profiling
from akka_game_of_life_tpu.runtime.chaos import CrashInjector
from akka_game_of_life_tpu.runtime.checkpoint import make_store
from akka_game_of_life_tpu.runtime.config import SimulationConfig
from akka_game_of_life_tpu.runtime.render import BoardObserver
from akka_game_of_life_tpu.utils.patterns import (
    place,
    random_grid,
    resolve_pattern,
)


def initial_board(config: SimulationConfig) -> np.ndarray:
    if config.pattern is not None:
        pattern, declared = resolve_pattern(config.pattern)
        if declared is not None:
            # An .rle file's header names the rule the pattern was designed
            # for; running it under a different rule is legal (exploration)
            # but usually a config mistake, so say so.
            try:
                mismatch = (
                    resolve_rule(declared).rulestring()
                    != resolve_rule(config.rule).rulestring()
                )
            except ValueError:
                mismatch = True  # header rule outside our rule space
            if mismatch:
                import logging

                logging.getLogger(__name__).warning(
                    "pattern %s declares rule %r but this run uses %r",
                    config.pattern,
                    declared,
                    config.rule,
                )
        board = np.zeros(config.shape, dtype=np.uint8)
        return place(board, pattern, config.pattern_offset)
    return random_grid(config.shape, density=config.density, seed=config.seed)


def _crosses(prev_epoch: int, epoch: int, every: int) -> bool:
    """Did the cadence boundary get crossed in (prev_epoch, epoch]?"""
    return every > 0 and (epoch // every) > (prev_epoch // every)


@contextlib.contextmanager
def _shield_sigint():
    """Defer ^C / SIGTERM across a critical section so (board, epoch) never
    tears.

    ``advance`` updates the board and the epoch as two separate statements;
    an interrupt landing between them would leave a stepped board labeled
    with the previous epoch, and an interrupt-checkpoint would then durably
    save that lie — a resumed run silently replays extra generations.  The
    shield swallows SIGINT/SIGTERM for the few bytecodes of the update and
    re-raises KeyboardInterrupt at the section's end, where state is
    consistent (the CLI maps SIGTERM to KeyboardInterrupt, so both signals
    share one graceful-shutdown path).  No-op off the main thread (signal()
    would raise there)."""
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    received = []
    shielded = []
    try:
        for sig in (signal.SIGINT, signal.SIGTERM):
            if signal.getsignal(sig) is None:
                # C-installed handler: it cannot be saved or re-installed
                # through the signal module (signal() would return None and
                # restoring None raises TypeError) — leave it untouched.
                continue
            shielded.append(
                (sig, signal.signal(sig, lambda s, f: received.append(s)))
            )
    except BaseException as e:
        # Roll back whatever was installed — including when an interrupt
        # from an already-shielded signal fires between the two installs —
        # so no shield lambda ever outlives this context.
        for sig, old_h in shielded:
            signal.signal(sig, old_h)
        if isinstance(e, ValueError):  # no signal support in this context
            yield
            return
        raise
    try:
        yield
    finally:
        for sig, old_h in shielded:
            signal.signal(sig, old_h)
    if received:
        raise KeyboardInterrupt


class Simulation:
    """One simulation run, resumable from checkpoints."""

    def __init__(
        self,
        config: SimulationConfig,
        observer: Optional[BoardObserver] = None,
        registry=None,
        tracer=None,
    ) -> None:
        self.config = config
        self.rule = resolve_rule(config.rule)
        # Observability: counters/gauges/histograms land in the process-wide
        # registry unless the embedder passes an isolated one; lifecycle
        # events append to the JSONL log when configured; spans (advance,
        # per-chunk, chaos crash/recover, checkpoint IO via timed()) record
        # into the tracer, whose flight ring dumps on injected crashes.
        self.metrics = registry if registry is not None else get_registry()
        self.tracer = tracer if tracer is not None else get_tracer()
        # Resolved once: observation runs at cadence inside the hot loop,
        # and instrument lookup takes the registry lock.
        self._m_obs_seconds = self.metrics.histogram("gol_obs_seconds")
        self._m_digest_checks = self.metrics.counter("gol_digest_checks_total")
        self._m_digest_seconds = self.metrics.histogram("gol_digest_seconds")
        if config.distributed:
            # Must happen before ANY backend init — including the checkpoint
            # store below (orbax queries process_index/count at construction)
            # and the jax.devices() query further down.  After this,
            # devices() is the GLOBAL list spanning every host.
            dist.initialize(
                config.coordinator_address,
                config.num_processes,
                config.process_id,
            )
            if config.fault_injection.enabled and not config.fault_injection.epoch_indexed:
                raise ValueError(
                    "wall-clock fault_injection with distributed=True is "
                    "unsupported: crash points are per-process wall-clock, so "
                    "ranks would replay different epochs and desynchronize "
                    "cross-host collectives.  Use the epoch-indexed schedule "
                    "(fault_injection.first_after_epochs / every_epochs) — "
                    "deterministic in simulation time, so every rank injects "
                    "at the same epoch — or the cluster control plane's "
                    "injector for per-worker chaos."
                )
        self._node = f"{config.role}:{jax.process_index()}"
        if tracer is None:
            # Role-label the process tracer so nodeless spans (checkpoint
            # IO on the async writer thread) attribute to this run.
            self.tracer.node = self._node
        self.tracer.flight.configure(
            directory=config.flight_dir, node=self._node
        )
        self.events = EventLog(
            config.log_events, node=self._node, recorder=self.tracer.flight
        )
        self._metrics_dumper = (
            MetricsDumper(self.metrics, config.metrics_file)
            if config.metrics_file
            else None
        )
        self.observer = observer or BoardObserver(
            render_every=config.render_every,
            render_max_cells=config.render_max_cells,
            metrics_every=config.metrics_every,
            log_file=config.log_file,
            registry=self.metrics,
        )
        self.store = (
            make_store(
                config.checkpoint_dir,
                config.checkpoint_format,
                registry=self.metrics,
                tracer=self.tracer,
            )
            if config.checkpoint_dir is not None
            else None
        )
        # Async npz saves: at most one in flight, on a single writer thread
        # (see SimulationConfig.checkpoint_async).  The pending entry is
        # (future, epoch); _ckpt_wait() drains it and surfaces write errors.
        self._ckpt_executor = None
        self._ckpt_pending = None
        if config.fault_injection.enabled and self.store is None:
            raise ValueError(
                "fault injection requires checkpoint_dir: a crash with no "
                "checkpoint to recover from would only restart from epoch 0"
            )
        self.injector = (
            CrashInjector(
                config.fault_injection,
                registry=self.metrics,
                flight=self.tracer.flight,
            )
            if config.fault_injection.enabled
            else None
        )
        self.crash_log: list[int] = []  # epochs at which injected crashes hit

        self.epoch = 0
        # obs_defer mode: observation records dispatched but not yet fetched
        # (resolved one chunk later, overlapped with the next stepper chunk).
        # Initialized before the actor-backend early return: advance()'s
        # resolve hook runs on every backend (a no-op when nothing defers).
        self._pending_obs: list = []

        self._actor_board = None
        self._actor_board_cls = None
        self._sparse = None
        if config.backend in ("actor", "actor-native"):
            if config.sparse_kernel:
                raise ValueError(
                    "sparse_kernel gates the stencil kernels; the per-cell "
                    "actor backends have no block structure to gate"
                )
            # The per-cell actor backend (BASELINE config 1): same Simulation
            # surface, reference-architecture engine underneath — interpreted
            # ("actor") or compiled C++ ("actor-native").
            board = initial_board(config)
            if self.store is not None and self.store.latest_epoch() is not None:
                ckpt = self.store.load()
                if ckpt.board.shape != config.shape:
                    raise ValueError(
                        f"checkpoint shape {ckpt.board.shape} != config {config.shape}"
                    )
                self.epoch = ckpt.epoch
                board = ckpt.board
            if config.backend == "actor-native":
                from akka_game_of_life_tpu.native.engine import NativeActorBoard

                self._actor_board_cls = NativeActorBoard
            else:
                from akka_game_of_life_tpu.runtime.actor_engine import ActorBoard

                self._actor_board_cls = ActorBoard
            self.mesh = None
            self.kernel = "dense"
            self._packed = False
            self._gen = False
            self._actor_board = self._actor_board_cls(board, self.rule)
            self._actor_epoch0 = self.epoch  # actor engine counts from 0
            self._steppers = {}
            self.board = board
            return

        n_dev = len(jax.devices())
        self._n_dev = n_dev
        # Activity-gated sparse stepping (intra-tile tier, docs/OPERATIONS.md
        # "Activity-gated sparse stepping"): a host-orchestrated block engine
        # that advances only blocks whose neighborhood changed last chunk.
        # Built below after kernel resolution; validated here so a bad combo
        # fails at __init__ with the knob's name, never mid-advance.
        if config.sparse_kernel:
            from akka_game_of_life_tpu.ops.sparse import SparseStepper, pick_block

            if self.rule.radius != 1:
                raise ValueError(
                    f"sparse_kernel gates radius-1 rules; {self.rule} "
                    f"(radius {self.rule.radius}) runs dense"
                )
            if config.mesh_shape is not None or config.distributed:
                raise ValueError(
                    "sparse_kernel is a single-host engine (the gather/"
                    "scatter runs on the host board); unset mesh_shape/"
                    "distributed or disable sparse_kernel"
                )
            if config.obs_defer:
                raise ValueError(
                    "sparse_kernel updates its host board in place between "
                    "chunks, so a deferred observation's handles could "
                    "alias mutated memory; obs_defer is a device-fetch "
                    "optimization the host engine does not need — disable "
                    "one of the two"
                )
            eff_block = pick_block(
                config.height, config.width, config.sparse_block
            )
            if config.steps_per_call > eff_block:
                raise ValueError(
                    f"steps_per_call={config.steps_per_call} exceeds the "
                    f"effective sparse block ({eff_block} cells for "
                    f"{config.height}x{config.width} with sparse_block="
                    f"{config.sparse_block}): the one-ring block dilation "
                    f"would miss influence"
                )
            self._sparse = SparseStepper(
                self.rule,
                config.shape,
                block=config.sparse_block,
                threshold=config.sparse_threshold,
            )
            self._m_sparse_active = self.metrics.gauge(
                "gol_sparse_active_blocks"
            )
            self._m_sparse_stepped = self.metrics.counter(
                "gol_sparse_blocks_stepped_total"
            )
            self._m_sparse_skipped = self.metrics.counter(
                "gol_sparse_blocks_skipped_total"
            )
            self._m_sparse_dense = self.metrics.counter(
                "gol_sparse_dense_chunks_total"
            )
        # Binary-totalistic AND plane-rule pallas shard via the Mosaic
        # sweeps inside shard_map (parallel/pallas_halo.py); the LtL pallas
        # kernel and the banded-matmul kernel have no sharded form, so
        # explicitly selecting them pins to one device — an explicit
        # mesh_shape then errors in _resolve_kernel rather than silently
        # ignoring either request.
        unsharded_kernel = (
            config.kernel == "pallas" and self.rule.kind == "ltl"
        ) or config.kernel == "matmul"
        self._use_mesh = config.mesh_shape is not None or (
            n_dev > 1 and not unsharded_kernel
        )
        self._kernel_auto = config.kernel == "auto"
        if self._sparse is not None:
            # The gated engine owns the layout: dense uint8 on the host,
            # active slabs jitted per chunk.  auto resolves to it; an
            # explicit packed/pallas kernel contradicts the request.
            if config.kernel not in ("auto", "dense"):
                raise ValueError(
                    f"sparse_kernel steps the dense-layout gated engine; "
                    f"kernel={config.kernel!r} conflicts (use auto or dense)"
                )
            self._use_mesh = False
            self.kernel = "dense"
        else:
            self.kernel = self._resolve_kernel()
        # Auto-selected pallas sizes its row block to the grid; explicit
        # pallas honors the config knob (validated in _resolve_kernel).
        self._pallas_block_rows = (
            self._auto_block_rows()
            if self._kernel_auto and self.kernel == "pallas"
            else config.pallas_block_rows
        )
        # LtL's pallas kernel is dense-layout (uint8 board in, uint8 out);
        # every other bitpack/pallas kernel is packed words/planes.
        self._packed = (
            self.kernel in ("bitpack", "pallas") and self.rule.kind != "ltl"
        )
        # Multi-state Generations rules on the packed kernel use bit planes
        # (ops/bitpack_gen.py): m = ceil(log2(states)) packed planes.
        self._gen = self._packed and not self.rule.is_binary
        if self._use_mesh:
            if self._packed:
                # Auto meshes go rows-only for packed boards (binary words
                # and Generations planes alike): a row of uint32 words is 32
                # cells wide per word, so narrow boards rarely split
                # column-wise; the row ring is the natural 1-D layout
                # (65536 rows / 8 devices = 8192-row shards on a v5e-8).
                self.mesh = make_grid_mesh(self._packed_mesh_shape())
                if self.kernel != "pallas":
                    # The pallas path plans its own exchange depth and was
                    # validated by _meshed_pallas_error in _resolve_kernel;
                    # halo_width is a bitpack-path knob irrelevant to it.
                    self._validate_packed_mesh()
            else:
                self.mesh = make_grid_mesh(config.mesh_shape)
                validate_tile_shape(
                    self.mesh, config.shape, config.halo_width, self.rule.radius
                )
        else:
            self.mesh = None
        self._steppers: Dict[int, Callable] = {}
        self._obs_fns: Dict[str, Callable] = {}

        board = words = None
        if self.store is not None and self.store.latest_epoch() is not None:
            ckpt = self.store.load(keep_packed=self._packed)
            self.epoch = ckpt.epoch
            if ckpt.packed32 is not None:
                words = ckpt.packed32
                expect = (config.height, config.width // 32)
                if self._gen:
                    expect = (bitpack_gen.n_planes(self.rule.states),) + expect
                if words.shape != expect:
                    raise ValueError(
                        f"checkpoint packed shape {words.shape} != config {expect}"
                    )
            else:
                if ckpt.board.shape != config.shape:
                    raise ValueError(
                        f"checkpoint shape {ckpt.board.shape} != config {config.shape}"
                    )
                board = ckpt.board
        else:
            board = initial_board(config)
        self.board = (
            self._words_to_device(words)
            if words is not None
            else self._to_device(board)
        )

    # -- kernel selection ----------------------------------------------------

    def _resolve_kernel(self) -> str:
        """Pick the stencil kernel the tpu backend steps with.  ``auto``
        prefers the Mosaic temporal-blocking Pallas kernel on a real TPU
        for binary rules (measured 8.5× the bitpack path on v5e —
        BASELINE.md) — single-device via the torus sweep, meshed via the
        sharded sweep (``parallel/pallas_halo.py``) — with a call-time
        fallback to bitpack if the Mosaic compile/run fails; elsewhere it
        prefers the bit-packed SWAR kernel whenever the rule and shape
        allow, falling back to the dense uint8 kernel for multi-state rules
        and odd widths; ``pallas`` is explicit opt-in (Mosaic-compiled)."""
        cfg = self.config
        kernel = cfg.kernel
        if kernel == "auto":
            if self.rule.kind == "ltl":
                # Radius-R counts live in ops/ltl.py's shift-add path; the
                # dense kernel slot carries them on every topology.
                return "dense"
            if cfg.width % 32:
                return "dense"
            if self._use_mesh and not self._packed_mesh_fits():
                # The bitpack feasibility gate applies even when pallas
                # would fit: auto-pallas carries a call-time bitpack
                # fallback, so the fallback path must be shardable too.
                return "dense"
            if self.rule.is_binary:
                # Generations stays on bitpack under auto: the gen Pallas
                # kernel is interpret-verified but not yet measured faster
                # on hardware, so only the proven binary win is defaulted.
                b = self._auto_block_rows()
                if (
                    jax.default_backend() == "tpu"
                    and b is not None
                    and (
                        not self._use_mesh
                        or self._meshed_pallas_error(b) is None
                    )
                ):
                    return "pallas"
                return "bitpack"
            # Generations rules: bit planes (0.25·m B/cell vs 1 B/cell dense).
            return "bitpack" if self.rule.states <= 256 else "dense"
        if kernel == "matmul":
            # The banded matrix-multiply family (ops/matmul_stencil.py):
            # explicit opt-in, single device, box neighborhoods, any rule
            # family.  plan_matmul re-checks all of it AND prices the
            # intermediates through ops/guard — called HERE so an
            # infeasible config (diamond, window self-wrap, over-cap
            # shapes) fails at __init__ with the knob's name, never
            # allocate-and-dies mid-advance (the recorded LtL OOM lesson).
            from akka_game_of_life_tpu.ops import matmul_stencil

            if self._use_mesh:
                raise ValueError(
                    "kernel=matmul is single-device (no sharded form); "
                    "use kernel=dense on a mesh"
                )
            matmul_stencil.plan_matmul(
                cfg.shape, self.rule.radius, "auto", self.rule.neighborhood
            )
            return kernel
        if kernel == "bitpack" and self.rule.kind == "ltl":
            raise ValueError(
                f"kernel=bitpack supports totalistic and wireworld rules "
                f"only; {self.rule} runs on kernel=dense (or kernel=pallas "
                f"for box neighborhoods)"
            )
        if kernel == "pallas" and self.rule.kind == "ltl":
            # The dense-layout VMEM-blocked LtL kernel (ops/pallas_ltl.py):
            # explicit opt-in, single device, box neighborhoods.  All of
            # the kernel's own preconditions are checked HERE so a bad
            # config fails at __init__, never mid-advance.
            from akka_game_of_life_tpu.ops.pallas_stencil import _round_up8

            if self.rule.neighborhood != "box":
                raise ValueError(
                    "kernel=pallas for ltl supports box neighborhoods only "
                    "(the diamond runs the cumsum path on kernel=dense)"
                )
            if self._use_mesh:
                raise ValueError(
                    "kernel=pallas for ltl is single-device (no sharded "
                    "form); use kernel=dense on a mesh"
                )
            hb = _round_up8(self.rule.radius)
            if cfg.pallas_block_rows % hb:
                raise ValueError(
                    f"kernel=pallas for ltl radius {self.rule.radius} "
                    f"requires pallas_block_rows % {hb} == 0, got "
                    f"{cfg.pallas_block_rows}"
                )
            self._require_block_rows_divides()
            return kernel
        if kernel in ("bitpack", "pallas"):
            if not self.rule.is_binary and self.rule.states > 256:
                raise ValueError(
                    f"kernel={kernel} supports at most 256 states, rule "
                    f"{self.rule} has {self.rule.states}"
                )
            if cfg.width % 32:
                raise ValueError(
                    f"kernel={kernel} requires width % 32 == 0, got {cfg.width}"
                )
        if kernel == "pallas":
            if self._use_mesh:
                err = self._meshed_pallas_error(cfg.pallas_block_rows)
                if err is not None:
                    if cfg.mesh_shape is None:
                        # No mesh was asked for: a config the meshed sweep
                        # can't shard but the single-device sweep can run
                        # falls back to one device (the pre-sharding
                        # behavior) instead of erroring on upgrade — and if
                        # both forms are infeasible, the error talks about
                        # the single-device constraint, not an implicit
                        # mesh the user never configured.
                        self._require_block_rows_divides()
                        self._use_mesh = False
                    else:
                        raise ValueError(err)
            else:
                self._require_block_rows_divides()
        return kernel

    def _require_block_rows_divides(self) -> None:
        cfg = self.config
        if cfg.height % cfg.pallas_block_rows:
            raise ValueError(
                f"kernel=pallas requires height % pallas_block_rows "
                f"({cfg.pallas_block_rows}) == 0, got {cfg.height}"
            )

    def _meshed_pallas_error(self, block_rows: int) -> Optional[str]:
        """Config-time feasibility of the sharded pallas path, or why not.

        Checks everything ``sharded_pallas_step_fn`` would reject at trace
        time — per-shard row-block alignment, a feasible exchange plan, and
        the word-column halo fitting the per-shard words — so an invalid
        config fails at __init__ with a ValueError, not mid-advance inside
        jit tracing.  The word check uses the deepest exchange any chunk
        could plan (``min(block_rows // 2, steps_per_call)``): trailing
        partial chunks plan independently and may go deeper than the full
        chunk's plan."""
        from akka_game_of_life_tpu.parallel.pallas_halo import plan_exchange

        cfg = self.config
        rows, cols = self._packed_mesh_shape()
        if cfg.height % rows:
            return (
                f"kernel=pallas on a {self._packed_mesh_shape()} mesh: "
                f"height {cfg.height} does not divide evenly into {rows} "
                f"row shards"
            )
        if (cfg.height // rows) % block_rows:
            return (
                f"kernel=pallas on a {self._packed_mesh_shape()} mesh "
                f"requires per-shard height ({cfg.height}/{rows} = "
                f"{cfg.height // rows}) to be a multiple of "
                f"pallas_block_rows={block_rows}"
            )
        try:
            plan_exchange(cfg.steps_per_call, block_rows)
        except ValueError as e:
            return f"kernel=pallas exchange plan infeasible: {e}"
        if (cfg.width // 32) % cols:
            return (
                f"kernel=pallas on a {self._packed_mesh_shape()} mesh: "
                f"{cfg.width // 32} packed words do not divide evenly "
                f"into {cols} column shards"
            )
        if cols > 1:
            hw = word_halo_width(min(block_rows // 2, cfg.steps_per_call))
            if (cfg.width // 32) // cols < hw:
                return (
                    f"kernel=pallas on a {self._packed_mesh_shape()} mesh: "
                    f"per-shard words {(cfg.width // 32) // cols} < word "
                    f"halo {hw} (up to {min(block_rows // 2, cfg.steps_per_call)} "
                    f"steps per exchange); use fewer column shards, a "
                    f"smaller block, or fewer steps per call"
                )
        return None

    def _auto_block_rows(self) -> Optional[int]:
        """The VMEM row block auto-selected pallas sweeps use: the largest
        8-multiple divisor of the per-shard height up to 128 (the
        measured-best block at 65536² — BASELINE.md), or None if the height
        has none (then auto stays on bitpack)."""
        from akka_game_of_life_tpu.ops.pallas_stencil import auto_block_rows

        h = self.config.height
        if self._use_mesh:
            rows = self._packed_mesh_shape()[0]
            if h % rows:
                return None
            h //= rows
        return auto_block_rows(h)

    def _with_bitpack_fallback(self, pallas_run: Callable, k: int) -> Callable:
        """Wrap an auto-selected pallas stepper so a Mosaic compile/run
        failure on the first call demotes the whole run to the bitpack
        kernel instead of crashing — ``auto`` promises the fastest kernel
        that *works*.  The first call is synced with a scalar fetch (on the
        axon platform ``block_until_ready`` does not actually block) so
        runtime failures surface here, inside the try, not at some later
        observation fetch outside it.  The fetch reads one element of the
        first *addressable shard*, never the global array: on a mesh,
        ``out.ravel()`` would force a full-board gather — and throw outright
        on a multi-host mesh, demoting a working pallas kernel."""
        proven = False

        def run(x):
            nonlocal proven
            if proven:
                return pallas_run(x)
            try:
                out = pallas_run(x)
                shards = getattr(out, "addressable_shards", None)
                probe = shards[0].data if shards else out
                _ = np.asarray(jax.device_get(probe.ravel()[0]))
                proven = True
                return out
            except Exception as e:  # noqa: BLE001 — any Mosaic failure demotes
                import sys

                print(
                    f"kernel=auto: pallas failed ({type(e).__name__}: {e}); "
                    f"falling back to bitpack",
                    file=sys.stderr,
                    flush=True,
                )
                self.kernel = "bitpack"
                self._steppers.clear()
                return self._stepper(k)(x)

        return run

    def _packed_mesh_shape(self) -> tuple:
        return self.config.mesh_shape or (self._n_dev, 1)

    def _packed_mesh_fits(self) -> bool:
        cfg = self.config
        rows, cols = self._packed_mesh_shape()
        words = cfg.width // 32
        s = self._halo_for(cfg.steps_per_call)
        return not (
            cfg.height % rows
            or words % cols
            or cfg.height // rows < s
            or words // cols < word_halo_width(s)
        )

    def _validate_packed_mesh(self) -> None:
        if not self._packed_mesh_fits():
            cfg = self.config
            raise ValueError(
                f"packed grid ({cfg.height} rows x {cfg.width // 32} words) "
                f"cannot shard over mesh {self._packed_mesh_shape()} with "
                f"{self._halo_for(cfg.steps_per_call)} steps per exchange; "
                f"use kernel=dense or a different mesh"
            )

    def _halo_for(self, k: int) -> int:
        halo = min(self.config.halo_width, k)
        while k % halo:
            halo -= 1
        return halo

    # -- device plumbing -----------------------------------------------------

    def _to_device(self, board: np.ndarray):
        if self._actor_board is not None:
            return board
        if self._sparse is not None:
            # The gated engine's board lives on the host (gather/scatter in
            # numpy; only active slabs visit the device).  A board arriving
            # here (initial, restore, replay) is one the stepper has never
            # produced, so its gate resets to all-active automatically.
            return np.asarray(board, dtype=np.uint8)
        if self._gen:
            return self._words_to_device(
                bitpack_gen.pack_gen_np(np.asarray(board), self.rule.states)
            )
        if self._packed:
            return self._words_to_device(bitpack.pack_np(np.asarray(board)))
        if self.mesh is not None:
            if jax.process_count() > 1:
                # Multi-host mesh: every process materializes only the
                # shards its own devices address.
                return dist.make_global_array(board, self.mesh)
            return shard_board(jnp.asarray(board), self.mesh)
        return jnp.asarray(board)

    def _words_to_device(self, words: np.ndarray):
        """Packed uint32 payload → the device-resident (and, on a mesh,
        sharded) board — the packed twin of :meth:`_to_device`.  2-D words
        for binary rules; (m, H, W/32) bit planes for Generations."""
        if self.mesh is not None:
            if self._gen:
                from jax.sharding import NamedSharding

                from akka_game_of_life_tpu.parallel.mesh import GEN_SPEC

                if jax.process_count() > 1:
                    return dist.make_global_array(words, self.mesh, spec=GEN_SPEC)
                return jax.device_put(
                    jnp.asarray(words), NamedSharding(self.mesh, GEN_SPEC)
                )
            if jax.process_count() > 1:
                return dist.make_global_array(words, self.mesh)
            return shard_packed2d(jnp.asarray(words), self.mesh)
        return jnp.asarray(words)

    def _stepper(self, k: int) -> Callable:
        """A k-epoch advance: jitted scan (cached per k) on the tpu backend,
        event-loop drive on the actor backend."""
        if self._actor_board is not None:

            def _actor_advance(_board):
                target = self.epoch - self._actor_epoch0 + k
                self._actor_board.advance_to(target)
                # Crash recovery rebuilds a fresh ActorBoard from the durable
                # checkpoint, never replays in place — so old history entries
                # are dead weight; bound them (unlike the reference's
                # forever-growing History maps, SURVEY.md §2 bug 5).
                self._actor_board.prune_histories_below(target - 1)
                return self._actor_board.board_at_current()

            return _actor_advance
        if self._sparse is not None:
            if k not in self._steppers:
                sp = self._sparse

                def _sparse_advance(board, _k=k):
                    dense_before = sp.dense_chunks
                    out = sp.step(board, _k)
                    # Gating observability after every chunk: live active
                    # fraction plus cumulative stepped/skipped block-chunks
                    # (the skip counter is the intra-tile win itself).
                    self._m_sparse_active.set(sp.last_active_blocks)
                    self._m_sparse_stepped.inc(sp.last_stepped_blocks)
                    self._m_sparse_skipped.inc(
                        sp.total_blocks - sp.last_stepped_blocks
                    )
                    if sp.dense_chunks > dense_before:
                        self._m_sparse_dense.inc()
                    return out

                self._steppers[k] = _sparse_advance
            return self._steppers[k]
        if k not in self._steppers:
            if self._gen:
                if self.mesh is None:
                    if self.kernel == "pallas":
                        from akka_game_of_life_tpu.ops import pallas_gen

                        self._steppers[k] = pallas_gen.gen_pallas_multi_step_fn(
                            self.rule,
                            k,
                            block_rows=self.config.pallas_block_rows,
                            vmem_limit_bytes=self.config.pallas_vmem_limit_bytes,
                            interpret=jax.default_backend() != "tpu",
                        )
                    else:
                        self._steppers[k] = bitpack_gen.gen_multi_step_fn(
                            self.rule, k
                        )
                elif self.kernel == "pallas":
                    from akka_game_of_life_tpu.parallel.pallas_halo import (
                        sharded_gen_pallas_step_fn,
                    )

                    self._steppers[k] = sharded_gen_pallas_step_fn(
                        self.mesh,
                        self.rule,
                        steps_per_call=k,
                        block_rows=self.config.pallas_block_rows,
                        vmem_limit_bytes=self.config.pallas_vmem_limit_bytes,
                        interpret=jax.default_backend() != "tpu",
                    )
                else:
                    from akka_game_of_life_tpu.parallel.packed_halo2d import (
                        sharded_gen_step_fn,
                    )

                    # Same width-k communication-avoiding exchange as the
                    # binary packed mesh path, extended over the (replicated)
                    # plane dim — one ppermute round per k epochs, not per
                    # epoch.
                    self._steppers[k] = sharded_gen_step_fn(
                        self.mesh,
                        self.rule,
                        steps_per_call=k,
                        halo_rows=self._halo_for(k),
                    )
            elif self._packed:
                if self.mesh is not None and self.kernel == "pallas":
                    from akka_game_of_life_tpu.parallel.pallas_halo import (
                        sharded_pallas_step_fn,
                    )

                    run = sharded_pallas_step_fn(
                        self.mesh,
                        self.rule,
                        steps_per_call=k,
                        block_rows=self._pallas_block_rows,
                        vmem_limit_bytes=self.config.pallas_vmem_limit_bytes,
                        interpret=jax.default_backend() != "tpu",
                    )
                    if self._kernel_auto:
                        run = self._with_bitpack_fallback(run, k)
                    self._steppers[k] = run
                elif self.mesh is not None:
                    self._steppers[k] = sharded_packed2d_step_fn(
                        self.mesh,
                        self.rule,
                        steps_per_call=k,
                        halo_rows=self._halo_for(k),
                    )
                elif self.kernel == "pallas":
                    from akka_game_of_life_tpu.ops import pallas_stencil

                    run = pallas_stencil.packed_multi_step_fn(
                        self.rule,
                        k,
                        block_rows=self._pallas_block_rows,
                        vmem_limit_bytes=self.config.pallas_vmem_limit_bytes,
                        # Mosaic needs a real TPU; everywhere else the kernel
                        # runs (slowly) in interpret mode, as documented on
                        # the config knob.
                        interpret=jax.default_backend() != "tpu",
                    )
                    if self._kernel_auto:
                        run = self._with_bitpack_fallback(run, k)
                    self._steppers[k] = run
                else:
                    self._steppers[k] = bitpack.packed_multi_step_fn(self.rule, k)
            elif self.mesh is not None:
                self._steppers[k] = sharded_step_fn(
                    self.mesh, self.rule, steps_per_call=k, halo_width=self._halo_for(k)
                )
            elif self.kernel == "matmul":
                # Banded matrix-multiply counts (dense uint8 layout, single
                # device — _resolve_kernel planned and guard-priced it).
                from akka_game_of_life_tpu.ops import matmul_stencil

                self._steppers[k] = matmul_stencil.matmul_multi_step_fn(
                    self.rule, k
                )
            elif self.kernel == "pallas":
                # Only the LtL pallas kernel reaches here (dense layout,
                # single device — _resolve_kernel enforced box + no mesh).
                from akka_game_of_life_tpu.ops import pallas_ltl

                self._steppers[k] = pallas_ltl.ltl_pallas_multi_step_fn(
                    self.rule,
                    k,
                    block_rows=self.config.pallas_block_rows,
                    vmem_limit_bytes=self.config.pallas_vmem_limit_bytes,
                    interpret=jax.default_backend() != "tpu",
                )
            else:
                self._steppers[k] = get_model(self.rule).run(k)
        return self._steppers[k]

    # -- core loop -----------------------------------------------------------

    def advance(self, epochs: Optional[int] = None) -> int:
        """Advance by exactly ``epochs`` generations (default:
        config.max_epochs).  Observation, pacing, checkpointing, and fault
        injection happen between chunks of ``steps_per_call`` generations —
        the on-device scan in between has zero host round-trips."""
        cfg = self.config
        target = self.epoch + (epochs if epochs is not None else (cfg.max_epochs or 0))
        # Anchor the metrics clock so the FIRST cadence crossing measures a
        # real interval (resumed runs with one remaining crossing would
        # otherwise observe nothing — no metrics line, no run summary).
        self.observer.start_clock(self.epoch)
        # Hot-loop instruments, resolved once (never inside the loop: name
        # lookup takes the registry lock).
        epochs_c = self.metrics.counter("gol_epochs_advanced_total")
        chunks_c = self.metrics.counter("gol_chunks_total")
        step_h = self.metrics.histogram("gol_step_seconds")
        epoch_g = self.metrics.gauge("gol_epoch")
        halo_c = self.metrics.counter("gol_halo_bytes_total")
        next_tick = time.monotonic()
        # The run-level trace root: chunk spans, chaos crash/recover spans,
        # and every timed()/checkpoint span inside the loop nest under it
        # via the thread-local stack.
        advance_span = self.tracer.span(
            "sim.advance", node=self._node,
            from_epoch=self.epoch, epochs=target - self.epoch,
        )
        advance_span.__enter__()
        try:
            while self.epoch < target:
                if cfg.tick_s > 0:
                    now = time.monotonic()
                    if now < next_tick:
                        time.sleep(next_tick - now)
                    next_tick = max(next_tick + cfg.tick_s, now)

                if self.injector is not None and (
                    self.injector.should_crash()
                    or self.injector.should_crash_at_epoch(self.epoch)
                ):
                    self._crash_and_recover()

                chunk = min(cfg.steps_per_call, target - self.epoch)
                prev = self.epoch
                chunk_t0 = time.perf_counter()
                with self.tracer.span(
                    "sim.chunk", node=self._node, epoch=prev, chunk=chunk
                ):
                    if self._sparse is not None:
                        # The gated engine mutates self.board IN PLACE, so
                        # the swap-only shield below would not be enough: an
                        # interrupt mid-scatter would leave a half-stepped
                        # board still labeled with the previous epoch, and
                        # the interrupt-checkpoint would durably save that
                        # lie.  Shield the WHOLE chunk (host-side and
                        # milliseconds on the gated path).
                        with _shield_sigint():
                            with profiling.annotate_epochs(
                                "advance_chunk", self.epoch
                            ):
                                self.board = self._stepper(chunk)(self.board)
                            self.epoch += chunk
                    else:
                        with profiling.annotate_epochs(
                            "advance_chunk", self.epoch
                        ):
                            new_board = self._stepper(chunk)(self.board)
                        with _shield_sigint():
                            # Atomic wrt ^C: an interrupt-checkpoint must
                            # never see a stepped board still labeled with
                            # the previous epoch.
                            self.board = new_board
                            self.epoch += chunk
                # Host-side chunk cost (dispatch → board swap): on a
                # synchronous backend this is the device time; under async
                # dispatch it is the host's share of the critical path.
                step_h.observe(time.perf_counter() - chunk_t0)
                epochs_c.inc(chunk)
                chunks_c.inc()
                epoch_g.set(self.epoch)
                if self.mesh is not None:
                    halo_c.inc(self._halo_bytes_per_chunk(chunk))
                # Resolve deferred observations from EARLIER cadence points
                # now, while the device is busy executing the chunk just
                # dispatched above — the host fetch round-trip rides under
                # device compute instead of serializing with it.
                self._obs_resolve()

                if _crosses(prev, self.epoch, cfg.render_every) or _crosses(
                    prev, self.epoch, cfg.metrics_every
                ):
                    self._observe(
                        render=_crosses(prev, self.epoch, cfg.render_every)
                    )
                if _crosses(prev, self.epoch, cfg.metrics_every):
                    self._dump_metrics()
                if self.store is not None and _crosses(
                    prev, self.epoch, cfg.checkpoint_every
                ):
                    self.checkpoint()
        except BaseException:
            # Best-effort flush on the way out, suppressed: a fetch against
            # a poisoned device (the likely state when a stepper chunk just
            # raised) must not replace the real exception — nor swallow a
            # KeyboardInterrupt heading for the interrupt-checkpoint path.
            try:
                self._obs_resolve()
            except Exception:  # noqa: BLE001
                pass
            raise
        finally:
            advance_span.set(reached=self.epoch).__exit__(None, None, None)
        # A cadence crossing on the final chunk has no next chunk to ride
        # under; flush it now (errors here are real and propagate).
        self._obs_resolve()
        return self.epoch

    def fast_forward(self, epochs: int) -> int:
        """Jump ``epochs`` generations in O(log epochs) device programs —
        XOR-linear (odd-rule) rules only (``ops/fastforward.py``; the
        linearity proof is ``ops/rules.linear_kernel``).  Before the jump
        commits, ``ff_certify_steps`` epochs are jump-vs-iterate
        digest-certified (sampled small T; the big jump rides the proven
        linear algebra).  Single-host; works on the dense, bit-packed
        (unpack → jump → repack), sparse-gated, and single-host meshed
        (gather → jump → re-shard) layouts.  Raises
        ``ValueError`` for non-linear rules, disabled config, or
        unsupported topologies — a rule outside the linear family is
        never silently fast-forwarded."""
        from akka_game_of_life_tpu.ops import (
            digest as odigest,
            fastforward,
        )

        cfg = self.config
        # Span validation FIRST (negative / past the 2^62 ceiling): the
        # refusal must land before the O(board) relayout gather and the
        # O(cert·area) certification do any work.
        epochs = fastforward._require_span(epochs)
        if epochs == 0:
            return self.epoch
        if not cfg.ff_enabled:
            raise ValueError(
                "fast_forward is disabled (ff_enabled=False / --ff-enabled "
                "off); advance() iterates as usual"
            )
        fastforward.kernel_offsets(self.rule)  # the linearity refusal
        if self._actor_board is not None:
            raise ValueError(
                "fast_forward needs the tpu backend's dense planes; the "
                "per-cell actor backends iterate"
            )
        if jax.process_count() > 1:
            raise ValueError(
                "fast_forward is single-host (a meshed jump gathers the "
                "board through this host and re-shards; a cross-host "
                "gather has no collective form yet) — run single-host or "
                "iterate"
            )
        t0 = time.perf_counter()
        with self.tracer.span(
            "sim.fastforward", node=self._node,
            epoch=self.epoch, epochs=epochs,
        ) as span:
            # One dense uint8 plane whatever the resident layout: packed
            # and meshed boards gather/unpack once (O(board), amortized
            # over the whole jump — the jump itself is O(board) work
            # regardless of T, so the relayout never dominates).
            relayout = (
                self._packed or self._sparse is not None
                or self.mesh is not None
            )
            board = jnp.asarray(self.board_host()) if relayout else self.board
            cert = min(epochs, cfg.ff_certify_steps)
            if cert:
                dig_t0 = time.perf_counter()
                try:
                    digest = fastforward.certify_jump(board, self.rule, cert)
                except RuntimeError:
                    self.metrics.counter("gol_digest_mismatches_total").inc()
                    raise
                self._m_digest_seconds.observe(time.perf_counter() - dig_t0)
                self._m_digest_checks.inc()
                span.set(certified_steps=cert,
                         digest=odigest.format_digest(digest))
            jumped = fastforward.fast_forward(board, self.rule, epochs)
            # Sync before the swap: dispatch is async, and the recorded
            # jump seconds must cover the compute, not just the enqueue.
            np.asarray(jax.device_get(jumped[(0,) * jumped.ndim]))
            with _shield_sigint():
                # Atomic wrt ^C, like advance(): an interrupt-checkpoint
                # must never see a jumped board at the pre-jump epoch.
                self.board = (
                    self._to_device(np.asarray(jumped)) if relayout else jumped
                )
                self.epoch += epochs
        self.metrics.counter("gol_ff_jumps_total").inc()
        self.metrics.counter("gol_ff_epochs_total").inc(epochs)
        self.metrics.histogram("gol_ff_seconds").observe(
            time.perf_counter() - t0
        )
        self.metrics.gauge("gol_epoch").set(self.epoch)
        self.events.emit("fast_forward", epoch=self.epoch, epochs=epochs)
        return self.epoch

    def _halo_bytes_per_chunk(self, k: int) -> int:
        """Analytic bytes one k-epoch chunk moves across the device mesh —
        the Casper-style data-movement signal (``gol_halo_bytes_total``).
        The exchange runs inside jit, so it cannot be counted at runtime;
        this mirrors the stepper's exchange plan instead: exchanges per
        chunk × perimeter bytes per exchange (packed layouts count uint32
        words, Generations multiplies by its plane count)."""
        cached = getattr(self, "_halo_bytes_cache", None)
        if cached is None:
            cached = self._halo_bytes_cache = {}
        if k not in cached:
            from akka_game_of_life_tpu.ops import bitpack_gen
            from akka_game_of_life_tpu.parallel.halo import exchange_bytes

            cfg = self.config
            if self._packed:
                # Packed exchange is asymmetric: the row phase moves `halo`
                # rows of uint32 words, the column phase only
                # word_halo_width(halo) word-columns (a 32-cell word column
                # covers the whole cell halo) — pricing columns at `halo`
                # words would overcount by up to 32x.
                mr, mc = self._packed_mesh_shape()
                th = cfg.height // mr
                tw = (cfg.width // 32) // mc
                halo = self._halo_for(k)
                per_tile = 2 * halo * tw if mr > 1 else 0
                if mc > 1:
                    wh = word_halo_width(halo)
                    per_tile += 2 * wh * (th + 2 * halo)
                per_exchange = mr * mc * per_tile * 4
                if self._gen:
                    per_exchange *= bitpack_gen.n_planes(self.rule.states)
            else:
                # The REAL mesh shape: auto meshes factor devices as square
                # as possible (make_grid_mesh(None)), not rows-only.
                mesh_shape = self.mesh.devices.shape
                tile = (cfg.height // mesh_shape[0], cfg.width // mesh_shape[1])
                halo = self._halo_for(k)
                per_exchange = exchange_bytes(
                    mesh_shape, tile, halo * self.rule.radius, itemsize=1
                )
            cached[k] = (k // max(1, self._halo_for(k))) * per_exchange
        return cached[k]

    def _dump_metrics(self) -> None:
        """Refresh the ``--metrics-file`` exposition (atomic; rank 0 only).

        Cadence gating lives in the caller; failure containment (warn once
        per outage, keep retrying — an unwritable observability file must
        never abort the simulation it observes) lives in the shared
        :class:`~akka_game_of_life_tpu.obs.dump.MetricsDumper`."""
        if self._metrics_dumper is None or jax.process_index() != 0:
            return
        # Device-memory watermarks ride the same cadence: the end-of-run
        # print promoted to cataloged gauges (gol_device_bytes_in_use /
        # _peak_), so the exposition carries them all run long.
        from akka_game_of_life_tpu.obs.programs import get_programs

        try:
            get_programs().refresh_device_gauges()
        except Exception:  # noqa: BLE001 — observability must not abort the run
            pass
        self._metrics_dumper.dump()

    # -- observation (device-side: nothing here is O(board) on host) ---------

    def _obs_fn(self, name: str, core: Callable) -> Callable:
        """A cached observation closure.  On a mesh the core runs under
        ``auto_axes`` with a replicated output spec: strided slices and
        word-index gathers have no unambiguous output sharding under the
        explicit-sharding mesh, and the outputs are tiny (a row vector, a
        <=max_cells² probe) so replication is the right answer."""
        if name not in self._obs_fns:
            if self.mesh is not None:
                from jax.sharding import PartitionSpec, auto_axes

                jitted = jax.jit(auto_axes(core, out_sharding=PartitionSpec()))
                mesh = self.mesh

                def call(*args):
                    with jax.set_mesh(mesh):
                        return jitted(*args)

                self._obs_fns[name] = call
            else:
                self._obs_fns[name] = jax.jit(core)
        return self._obs_fns[name]

    def _digest_fn(self) -> Callable:
        """The board-digest closure for this run's layout (cached): dense,
        packed words, or Generations planes — and, on a mesh, the
        shard_map+psum fold (``parallel/digest.py``) so certification
        never gathers a board.  The sharded-Pallas kernel steps the same
        packed2d layout as bitpack, so one fold covers both."""
        if "digest" not in self._obs_fns:
            from akka_game_of_life_tpu.ops import digest as odigest

            cfg = self.config
            if self.mesh is not None:
                from akka_game_of_life_tpu.parallel import digest as pdigest

                if self._gen:
                    fn = pdigest.sharded_gen_digest_fn(
                        self.mesh, cfg.shape, self.rule.states
                    )
                elif self._packed:
                    fn = pdigest.sharded_packed2d_digest_fn(self.mesh, cfg.shape)
                else:
                    fn = pdigest.sharded_dense_digest_fn(self.mesh, cfg.shape)
            elif self._gen:
                fn = jax.jit(lambda b: odigest.digest_planes(b, cfg.width))
            elif self._packed:
                fn = jax.jit(lambda b: odigest.digest_packed(b, cfg.width))
            else:
                fn = jax.jit(odigest.digest_dense)
            self._obs_fns["digest"] = fn
        return self._obs_fns["digest"]

    def board_digest(self) -> int:
        """The 64-bit on-device digest of the CURRENT board — ~8 fetched
        bytes at any board size (the certification primitive; cadence
        observation uses the same closure).  Works on every kernel/mesh
        combination and the actor backends."""
        from akka_game_of_life_tpu.ops import digest as odigest

        if self._actor_board is not None:
            return odigest.value(odigest.digest_dense_np(np.asarray(self.board)))
        lanes = np.asarray(
            dist.fetch(self._digest_fn()(self.board)), dtype=np.uint32
        )
        return odigest.value(lanes)

    def _probe_due(self, render: bool) -> bool:
        """Window probes follow the same gate as rendered frames (an exact
        ``render_every`` multiple) so probe epochs always line up with frame
        epochs — and a suppressed frame never pays a window fetch."""
        cfg = self.config
        return (
            render
            and cfg.probe_window is not None
            and cfg.render_every > 0
            and self.epoch % cfg.render_every == 0
        )

    def _observe(self, *, render: bool) -> None:
        """Population (always) and a strided render probe (at render cadence),
        both computed on device; only a chunk-sum vector and a <=max_cells²
        sample cross to the host — the standalone runtime's answer to
        VERDICT.md weak #4 (the old path shipped the whole board, a full
        cross-host allgather at 65536²).  The observation's wall cost
        (dispatch + fetches) is measured and surfaced on the metrics line so
        the stepper's own per-epoch time is separable from cadence overhead
        (VERDICT.md round-3 weak #3)."""
        if self._actor_board is not None:
            if jax.process_index() == 0:
                self.observer.observe(
                    self.epoch,
                    np.asarray(self.board),
                    digest=(
                        self.board_digest() if self.config.obs_digest else None
                    ),
                )
                if self._probe_due(render):
                    self.observer.observe_window(
                        self.epoch,
                        self.board_window(*self.config.probe_window),
                        self.config.probe_window,
                    )
            return
        if self.config.obs_defer:
            # Dispatch-only: the tiny device results are fetched by
            # _obs_resolve one chunk later, under the next chunk's compute.
            self._pending_obs.append(self._obs_dispatch(render))
            return
        # Sync the stepper chain before starting the observation clock: the
        # stepper dispatch is async (and on the axon platform
        # block_until_ready does not actually block), so without this the
        # population fetch below would absorb the whole stepper time and the
        # obs/stepper breakdown on the metrics line would be meaningless.
        # One scalar from the first addressable shard — never the global
        # array (a full gather on a mesh).
        shards = getattr(self.board, "addressable_shards", None)
        probe = shards[0].data if shards else self.board
        # Single-element index, never ravel(): an eager ravel materializes a
        # full flattened copy of the shard before the scalar is taken.
        np.asarray(jax.device_get(probe[(0,) * probe.ndim]))
        obs_t0 = time.perf_counter()  # BEFORE dispatch: obs ms = dispatch+fetch
        self._obs_emit(self._obs_dispatch(render), obs_t0)

    def _obs_dispatch(self, render: bool) -> dict:
        """Dispatch the cadence observation on device and return a record of
        un-fetched handles: population chunk-sums (always), the strided
        render sample (at render cadence), and the exact-cell probe window.
        Nothing here touches the host."""
        cfg = self.config
        from akka_game_of_life_tpu.runtime.render import sample_strides

        if self._gen:
            m = bitpack_gen.n_planes(self.rule.states)

            def row_pops(p):
                alive = bitpack_gen._eq_const([p[k] for k in range(m)], 1)
                return bitpack.population_rows(alive)

        elif self._packed:
            row_pops = bitpack.population_rows
        else:
            row_pops = lambda b: jnp.sum((b == 1).astype(jnp.uint32), axis=1)
        # Device-side second reduction: (H,) exact uint32 row counts fold to
        # n_chunks partial sums, so the fetch is O(chunks) bytes, not O(H) —
        # 256 KB → 1 KB at 65536² over the slow tunnel fetch path.  Chunk
        # cell coverage stays far below 2³², keeping each uint32 partial
        # exact; the host total still sums in int64.
        n_chunks = min(cfg.height, max(256, cfg.height * cfg.width // 2**31))

        def pop_core(b):
            rows = row_pops(b)
            pad = (-rows.shape[0]) % n_chunks
            if pad:
                rows = jnp.pad(rows, (0, pad))
            return jnp.sum(rows.reshape(n_chunks, -1), axis=1)

        rec: dict = {
            "epoch": self.epoch,
            "pops": self._obs_fn("pop", pop_core)(self.board),
            "view": None,
            "strides": sample_strides(cfg.shape, cfg.render_max_cells),
            "win": None,
            # Digest mode: the certificate handle is dispatched with the
            # rest of the observation and fetched (8 bytes) alongside it —
            # riding obs_defer's deferred fetch like every other handle.
            "digest": (
                self._digest_fn()(self.board) if cfg.obs_digest else None
            ),
        }
        if render:
            sy, sx = rec["strides"]
            if self._gen:
                plane_sample = bitpack.sample_packed_core(sy, sx, cfg.width)
                m = bitpack_gen.n_planes(self.rule.states)

                def sample_core(p):
                    out = plane_sample(p[0])
                    for k in range(1, m):
                        out = out | (plane_sample(p[k]) << k)
                    return out

            elif self._packed:
                sample_core = bitpack.sample_packed_core(sy, sx, cfg.width)
            else:
                sample_core = lambda b: b[::sy, ::sx]
            rec["view"] = self._obs_fn(f"sample_{sy}_{sx}", sample_core)(
                self.board
            )
        if self._probe_due(render):
            rec["win"] = self._window_request(*cfg.probe_window)
        return rec

    def _obs_emit(self, rec: dict, t0: float, on_fetched=None) -> None:
        """Fetch a dispatched observation record and emit observer lines.
        ``t0`` is where the obs clock started: dispatch time in sync mode
        (obs ms = dispatch + fetch), resolve time in deferred mode (obs ms =
        the residual fetch cost left on the critical path).  ``on_fetched``
        fires once every RAW device fetch has succeeded — immediately, and
        in particular BEFORE the window's host-side ``post()`` and any
        observer write — so the deferred queue marks the record consumed
        the moment only host work remains.  Only a device fetch failure may
        leave the record queued (the caller's retry/flush policy); a
        deterministic ``post()`` or write error must consume it — it would
        otherwise re-queue and poison every subsequent flush, and the
        metrics line lands before the window line, so a requeue would also
        duplicate it on the next flush."""
        cfg = self.config
        pops = np.asarray(dist.fetch(rec["pops"]), dtype=np.int64)
        view = dist.fetch(rec["view"]) if rec["view"] is not None else None
        win_raw = post = None
        if rec["win"] is not None:
            handle, post = rec["win"]
            win_raw = dist.fetch(handle)
        digest = None
        if rec.get("digest") is not None:
            from akka_game_of_life_tpu.ops import digest as odigest

            dig_t0 = time.perf_counter()
            with self.tracer.span(
                "obs.digest", node=self._node, epoch=rec["epoch"]
            ) as sp:
                digest = odigest.value(
                    np.asarray(dist.fetch(rec["digest"]), dtype=np.uint32)
                )
                sp.set(digest=odigest.format_digest(digest))
            self._m_digest_seconds.observe(time.perf_counter() - dig_t0)
            self._m_digest_checks.inc()
        # Every raw device fetch succeeded: consume the record NOW, before
        # any host-side post() or observer write can fail deterministically
        # (see the docstring's poisoned-flush contract).
        if on_fetched is not None:
            on_fetched()
        population = int(pops.sum())
        win = post(win_raw) if win_raw is not None else None
        obs_seconds = time.perf_counter() - t0
        self._m_obs_seconds.observe(obs_seconds)
        if jax.process_index() == 0:
            self.observer.observe_summary(
                rec["epoch"],
                population,
                cfg.shape,
                view,
                rec["strides"],
                obs_seconds=obs_seconds,
                digest=digest,
            )
            if win is not None:
                self.observer.observe_window(
                    rec["epoch"], win, cfg.probe_window
                )

    def _obs_resolve(self) -> None:
        """Emit every pending deferred observation, oldest first (no-op in
        sync mode or when nothing is pending)."""
        while self._pending_obs:
            # Pop once the fetches succeed (via on_fetched), not after the
            # full emit: a failed device fetch leaves the record queued for
            # the caller's retry/flush policy, but a failed observer WRITE
            # consumes it — its metrics line may already be out, and a
            # requeue would duplicate that line on the next flush.
            self._obs_emit(
                self._pending_obs[0],
                time.perf_counter(),
                on_fetched=lambda: self._pending_obs.pop(0),
            )

    # -- failure & recovery --------------------------------------------------

    def _crash_and_recover(self) -> None:
        """An injected crash: in-memory state is lost; recover from the
        latest checkpoint and deterministically replay the missed epochs."""
        assert self.store is not None
        # Flush deferred observations first: their device handles reference
        # the pre-crash board, whose values (for their epochs) are exactly
        # what deterministic replay reproduces — emit them in order before
        # the epoch rewinds.
        self._obs_resolve()
        # A save still in flight must land before the restore reads the
        # store — the crash loses device state, not the writer thread.
        self._ckpt_wait()
        target = self.epoch
        self.crash_log.append(target)
        with self.tracer.span(
            "chaos.crash", node=self._node, epoch=target
        ):
            self.events.emit("crash_injected", epoch=target)
            self.board = None  # the crash: live state gone
            # The crash IS the post-mortem moment: dump the last-N ring
            # (spans up to and including this one's parents, lifecycle
            # events) before recovery overwrites the story.
            self.tracer.flight.dump("crash_injected", node=self._node)
        recover_span = self.tracer.span(
            "chaos.recover", node=self._node, epoch=target
        )
        recover_span.__enter__()
        restored_epoch = None
        try:
            ckpt = (
                self.store.load(keep_packed=self._packed)
                if self.store.latest_epoch() is not None
                else None
            )
            if ckpt is None:
                self.epoch = 0
                self.board = self._to_device(initial_board(self.config))
            elif ckpt.packed32 is not None:
                self.epoch = ckpt.epoch
                self.board = self._words_to_device(ckpt.packed32)
            else:
                self.epoch = ckpt.epoch
                restored = ckpt.board
                if self._actor_board is not None:
                    # Fresh actors reseeded from the restored board
                    # (supervision restart at the checkpoint, not epoch 0).
                    self._actor_board = self._actor_board_cls(restored, self.rule)
                    self._actor_epoch0 = self.epoch
                self.board = self._to_device(restored)
            restored_epoch = self.epoch
            while self.epoch < target:
                # Replay: recompute the lost epochs (deterministic rule ⇒
                # the trajectory is bit-identical to the pre-crash one).
                # Reuses the steps_per_call stepper so no extra compilation
                # beyond at most one partial chunk.  The gated engine's
                # in-place chunks get the same interrupt shield as the main
                # loop (a torn board must never be checkpointable).
                chunk = min(self.config.steps_per_call, target - self.epoch)
                if self._sparse is not None:
                    with _shield_sigint():
                        self.board = self._stepper(chunk)(self.board)
                        self.epoch += chunk
                else:
                    self.board = self._stepper(chunk)(self.board)
                    self.epoch += chunk
        finally:
            if restored_epoch is not None:
                recover_span.set(
                    restored_from=restored_epoch,
                    replayed=target - restored_epoch,
                )
            recover_span.__exit__(None, None, None)
        self.metrics.counter("gol_chaos_recovered_total").inc()
        self.metrics.counter("gol_chaos_replay_epochs_total").inc(
            target - restored_epoch
        )
        self.events.emit(
            "crash_recovered",
            epoch=target,
            restored_from=restored_epoch,
            replayed=target - restored_epoch,
        )

    def checkpoint(self, host_board: Optional[np.ndarray] = None) -> None:
        if self.store is None:
            raise RuntimeError("no checkpoint_dir configured")
        meta = {"height": self.config.height, "width": self.config.width}
        if self.config.obs_digest:
            # The checkpoint's state certificate, computed ON DEVICE from
            # the live board (~8 fetched bytes — never a host-side O(board)
            # recompute): the store records it so `checkpoints --validate`
            # can re-derive and compare.  Runs BEFORE the npz rank gate —
            # the mesh digest is a psum collective every rank must execute.
            from akka_game_of_life_tpu.ops import digest as odigest

            meta["digest"] = odigest.format_digest(self.board_digest())
        npz = self.config.checkpoint_format == "npz"
        if npz and jax.process_count() > 1 and jax.process_index() != 0:
            # The npz store is a host-side writer: exactly one process owns
            # the file.  (The orbax store is multihost-aware — every process
            # participates in a sharded save — so it is not gated.)
            if host_board is None:
                # Keep the collective fetch in lockstep with rank 0.
                dist.fetch(self.board) if self._packed else self.board_host()
            # No rank may run past a checkpoint epoch before the file is
            # durable: an epoch-indexed crash right after this boundary makes
            # every rank load the store, and a rank racing ahead of rank 0's
            # write would restore an older epoch and replay a different
            # number of collective steps — deadlocking the mesh.
            dist.barrier(f"ckpt-{self.epoch}")
            return

        # Bind the snapshot NOW: an async save runs while the main loop
        # replaces self.board/self.epoch, and jax arrays are immutable, so
        # capturing the references (not self) is what makes the overlap
        # correct — the checkpoint is of this epoch, whatever runs next.
        # The sparse engine's host board is the one MUTABLE layout (updated
        # in place between chunks): snapshot it by copy, or the async
        # writer would serialize a live-mutating buffer.
        epoch, board = self.epoch, self.board
        if self._sparse is not None:
            board = np.array(board, copy=True)
        rulestr = self.rule.rulestring()
        self.events.emit(
            "checkpoint_requested",
            epoch=epoch,
            format=self.config.checkpoint_format,
        )
        if self._packed and host_board is None:
            # Packed runs never unpack for a checkpoint: npz receives the
            # (H, W/32) uint32 words (0.25 B/cell host transfer); orbax saves
            # the packed device array in place, tagged so load() can decode.
            def _save():
                if npz:
                    words = np.asarray(dist.fetch(board), dtype=np.uint32)
                    self.store.save_packed32(
                        epoch,
                        words,
                        self.config.shape,
                        rulestr,
                        meta=meta,
                    )
                else:
                    self.store.save(
                        epoch,
                        board,
                        rulestr,
                        meta={**meta, "layout": "packed32"},
                    )

        else:
            if host_board is None and npz and jax.process_count() > 1:
                # npz is a host-side writer and needs the whole board; orbax
                # keeps its device-native sharded save — no cross-host gather.
                host_board = self.board_host()
            if host_board is None:
                # The store decides where the bytes come from: the orbax
                # store saves the (possibly sharded) device array without
                # host gather; the npz store gathers internally.
                host_board = board

            def _save():
                self.store.save(epoch, host_board, rulestr, meta=meta)

        if npz and self.config.checkpoint_async and jax.process_count() == 1:
            # Overlap the save (device fetch + file write) with compute.
            # One save in flight at a time: draining the previous one first
            # bounds memory (one extra board snapshot alive) and keeps the
            # store's write+GC single-threaded.
            self._ckpt_wait()
            if self._ckpt_executor is None:
                import concurrent.futures

                self._ckpt_executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ckpt"
                )

            def _timed_save():
                t0 = time.perf_counter()
                _save()
                return (time.perf_counter() - t0) * 1e3

            self._ckpt_pending = (self._ckpt_executor.submit(_timed_save), epoch)
        elif self.config.metrics_every:
            # Checkpoint cost is an operational metric: surface it alongside
            # the throughput lines.
            with profiling.timed(
                f"checkpoint@{epoch}",
                out=self.observer.out,
                registry=self.metrics,
                span="checkpoint",
            ):
                _save()
        else:
            _save()
        if npz and jax.process_count() > 1:
            # Rank 0's side of the durability barrier (see the gated branch).
            dist.barrier(f"ckpt-{epoch}")

    def flush(self) -> None:
        """Make every requested checkpoint durable without closing: block
        until the in-flight async save (if any) is on disk.  The supported
        durability point for embedders that resume a second Simulation from
        the same directory, or inspect the store, while this one stays
        live.  Raises the writer's error, if any, here."""
        self._ckpt_wait()
        if self.store is not None:
            self.store.wait()

    def _ckpt_wait(self) -> None:
        """Drain the in-flight async save (no-op if none).  Raises the
        writer's exception here, on the thread that asked for durability."""
        if self._ckpt_pending is None:
            return
        future, epoch = self._ckpt_pending
        self._ckpt_pending = None
        ms = future.result()
        if self.config.metrics_every:
            print(
                f"[profile] checkpoint@{epoch} (async write): {ms:.2f} ms",
                file=self.observer.out,
                flush=True,
            )

    def board_window(self, y0: int, y1: int, x0: int, x1: int) -> np.ndarray:
        """A (y1-y0, x1-x0) uint8 window of the board, computed device-side
        and fetched O(window) — never O(board).  The at-scale correctness
        probe: a Gosper-gun region at 65536², where ``board_host()`` would
        gather 4 GiB, costs a few hundred bytes (the north-star criterion —
        gun period preserved across kill/restart — stays checkable at the
        headline size).  Works on every kernel/mesh combination; on a mesh
        the slice runs under ``auto_axes`` with a replicated output like the
        render sample."""
        if not (0 <= y0 < y1 <= self.config.height):
            raise ValueError(f"bad row window [{y0}, {y1})")
        if not (0 <= x0 < x1 <= self.config.width):
            raise ValueError(f"bad col window [{x0}, {x1})")
        if self._actor_board is not None:
            return np.asarray(self.board[y0:y1, x0:x1])
        handle, post = self._window_request(y0, y1, x0, x1)
        return post(dist.fetch(handle))

    def _window_request(self, y0: int, y1: int, x0: int, x1: int):
        """Dispatch the probe-window slice on device; returns ``(handle,
        post)`` where ``post(fetched)`` finishes the O(window) host work
        (unpack + trim on packed layouts).  Split from ``board_window`` so
        obs_defer can fetch the handle a chunk later.

        The slice cores take the offsets as TRACED scalars and cache by
        window SHAPE only — a probe that moves across the board (glider
        tracking) reuses one compiled executable instead of leaking a
        fresh jit per position."""
        if self._packed or self._gen:
            # Packed: slice whole uint32 word columns on device, unpack the
            # tiny host copy, trim to the exact cell window.
            w0, w1 = x0 // bitpack.LANE_BITS, -(-x1 // bitpack.LANE_BITS)
            rows, wws = y1 - y0, w1 - w0
            if self._gen:
                m = bitpack_gen.n_planes(self.rule.states)
                core = lambda b, r0, c0: jax.lax.dynamic_slice(
                    b, (0, r0, c0), (m, rows, wws)
                )
                name = f"win_gen_{rows}x{wws}"
            else:
                core = lambda b, r0, c0: jax.lax.dynamic_slice(
                    b, (r0, c0), (rows, wws)
                )
                name = f"win_packed_{rows}x{wws}"
            unpack = (
                bitpack_gen.unpack_gen_np if self._gen else bitpack.unpack_np
            )
            off = x0 - w0 * bitpack.LANE_BITS

            def post(fetched) -> np.ndarray:
                cells = unpack(np.asarray(fetched, dtype=np.uint32))
                return cells[:, off : off + (x1 - x0)]

            return self._obs_fn(name, core)(self.board, y0, w0), post
        rows, cols = y1 - y0, x1 - x0
        core = lambda b, r0, c0: jax.lax.dynamic_slice(b, (r0, c0), (rows, cols))
        return (
            self._obs_fn(f"win_dense_{rows}x{cols}", core)(self.board, y0, x0),
            np.asarray,
        )

    def board_host(self) -> np.ndarray:
        """The full board as host uint8 — O(board); for final renders, tests,
        and small boards (the steady-state loop never calls this)."""
        if self._gen:
            return bitpack_gen.unpack_gen_np(
                np.asarray(dist.fetch(self.board), dtype=np.uint32)
            )
        if self._packed:
            return bitpack.unpack_np(
                np.asarray(dist.fetch(self.board), dtype=np.uint32)
            )
        if self._sparse is not None:
            # The gated engine mutates its board in place between chunks;
            # hand callers their own copy, never a live view.
            return np.array(self.board, copy=True)
        return dist.fetch(self.board)

    def close(self) -> None:
        """Finalize: block until async checkpoint saves are durable.  Must be
        called before process exit when checkpointing is enabled — an async
        (npz writer-thread or orbax) save still in flight at interpreter
        shutdown is lost."""
        try:
            self._ckpt_wait()
        finally:
            # Even when the drained save failed, release everything: the
            # writer pool must not leak and the observer's log-file sink
            # must flush before the error propagates.
            if self._ckpt_executor is not None:
                self._ckpt_executor.shutdown(wait=True)
                self._ckpt_executor = None
            if self.store is not None:
                self.store.close()
            # Final exposition + trace dumps + event-log close: the durable
            # tail of the run's observability (the interval dumps only
            # cover metrics cadence points).
            try:
                self._dump_metrics()
                if self.config.trace_file and jax.process_index() == 0:
                    try:
                        self.tracer.write(self.config.trace_file)
                    except OSError as e:
                        print(f"trace-file write failed: {e}", flush=True)
            finally:
                self.events.emit("sim_closed", epoch=self.epoch)
                self.events.close()
                self.observer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
