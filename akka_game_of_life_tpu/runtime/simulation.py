"""Standalone simulation driver: config → board → stepper → observer.

This is the single-process equivalent of the reference's whole cluster — the
coordinator loop that ``BoardCreator`` implements with timers and message
fan-out (``BoardCreator.scala:105-116``) becomes a host loop around a jitted
(and, multi-device, sharded) step function.  Pacing is free-running by
default; set ``tick_s`` to reproduce the reference's fixed wall-clock cadence.

Crash recovery is checkpoint + deterministic replay: a crash (injected by the
chaos scheduler, or a real kill + re-launch) discards in-memory state, the
latest checkpoint is restored, and the missed epochs are recomputed — the
same trajectory, because the update is deterministic.  This is the TPU-native
version of the reference's replay-from-neighbor-histories recovery
(SURVEY.md §3.3) without its unbounded memory."""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from akka_game_of_life_tpu.models import get_model
from akka_game_of_life_tpu.ops.rules import resolve_rule
from akka_game_of_life_tpu.parallel import (
    distributed as dist,
    make_grid_mesh,
    shard_board,
    sharded_step_fn,
    validate_tile_shape,
)
from akka_game_of_life_tpu.runtime import profiling
from akka_game_of_life_tpu.runtime.chaos import CrashInjector
from akka_game_of_life_tpu.runtime.checkpoint import make_store
from akka_game_of_life_tpu.runtime.config import SimulationConfig
from akka_game_of_life_tpu.runtime.render import BoardObserver
from akka_game_of_life_tpu.utils.patterns import pattern_board, random_grid


def initial_board(config: SimulationConfig) -> np.ndarray:
    if config.pattern is not None:
        return pattern_board(config.pattern, config.shape, config.pattern_offset)
    return random_grid(config.shape, density=config.density, seed=config.seed)


def _crosses(prev_epoch: int, epoch: int, every: int) -> bool:
    """Did the cadence boundary get crossed in (prev_epoch, epoch]?"""
    return every > 0 and (epoch // every) > (prev_epoch // every)


class Simulation:
    """One simulation run, resumable from checkpoints."""

    def __init__(
        self,
        config: SimulationConfig,
        observer: Optional[BoardObserver] = None,
    ) -> None:
        self.config = config
        self.rule = resolve_rule(config.rule)
        if config.distributed:
            # Must happen before ANY backend init — including the checkpoint
            # store below (orbax queries process_index/count at construction)
            # and the jax.devices() query further down.  After this,
            # devices() is the GLOBAL list spanning every host.
            dist.initialize(
                config.coordinator_address,
                config.num_processes,
                config.process_id,
            )
            if config.fault_injection.enabled:
                raise ValueError(
                    "fault_injection with distributed=True is unsupported: "
                    "crash points are per-process wall-clock, so ranks would "
                    "replay different epochs and desynchronize cross-host "
                    "collectives (use the cluster control plane's injector "
                    "for multi-process chaos)"
                )
        self.observer = observer or BoardObserver(
            render_every=config.render_every,
            render_max_cells=config.render_max_cells,
            metrics_every=config.metrics_every,
            log_file=config.log_file,
        )
        self.store = (
            make_store(config.checkpoint_dir, config.checkpoint_format)
            if config.checkpoint_dir is not None
            else None
        )
        if config.fault_injection.enabled and self.store is None:
            raise ValueError(
                "fault injection requires checkpoint_dir: a crash with no "
                "checkpoint to recover from would only restart from epoch 0"
            )
        self.injector = (
            CrashInjector(config.fault_injection)
            if config.fault_injection.enabled
            else None
        )
        self.crash_log: list[int] = []  # epochs at which injected crashes hit

        self.epoch = 0
        board = initial_board(config)
        if self.store is not None and self.store.latest_epoch() is not None:
            ckpt = self.store.load()
            if ckpt.board.shape != config.shape:
                raise ValueError(
                    f"checkpoint shape {ckpt.board.shape} != config {config.shape}"
                )
            self.epoch = ckpt.epoch
            board = ckpt.board

        self._actor_board = None
        self._actor_board_cls = None
        if config.backend in ("actor", "actor-native"):
            # The per-cell actor backend (BASELINE config 1): same Simulation
            # surface, reference-architecture engine underneath — interpreted
            # ("actor") or compiled C++ ("actor-native").
            if config.backend == "actor-native":
                from akka_game_of_life_tpu.native.engine import NativeActorBoard

                self._actor_board_cls = NativeActorBoard
            else:
                from akka_game_of_life_tpu.runtime.actor_engine import ActorBoard

                self._actor_board_cls = ActorBoard
            self.mesh = None
            self._actor_board = self._actor_board_cls(board, self.rule)
            self._actor_epoch0 = self.epoch  # actor engine counts from 0
            self._steppers = {}
            self.board = board
            return

        n_dev = len(jax.devices())
        self._use_mesh = config.mesh_shape is not None or n_dev > 1
        if self._use_mesh:
            self.mesh = make_grid_mesh(config.mesh_shape)
            validate_tile_shape(self.mesh, config.shape, config.halo_width)
        else:
            self.mesh = None
        self._steppers: Dict[int, Callable] = {}
        self.board = self._to_device(board)

    # -- device plumbing -----------------------------------------------------

    def _to_device(self, board: np.ndarray):
        if self._actor_board is not None:
            return board
        if self.mesh is not None:
            if jax.process_count() > 1:
                # Multi-host mesh: every process materializes only the
                # shards its own devices address.
                return dist.make_global_array(board, self.mesh)
            return shard_board(jnp.asarray(board), self.mesh)
        return jnp.asarray(board)

    def _stepper(self, k: int) -> Callable:
        """A k-epoch advance: jitted scan (cached per k) on the tpu backend,
        event-loop drive on the actor backend."""
        if self._actor_board is not None:

            def _actor_advance(_board):
                target = self.epoch - self._actor_epoch0 + k
                self._actor_board.advance_to(target)
                # Crash recovery rebuilds a fresh ActorBoard from the durable
                # checkpoint, never replays in place — so old history entries
                # are dead weight; bound them (unlike the reference's
                # forever-growing History maps, SURVEY.md §2 bug 5).
                self._actor_board.prune_histories_below(target - 1)
                return self._actor_board.board_at_current()

            return _actor_advance
        if k not in self._steppers:
            if self.mesh is not None:
                halo = min(self.config.halo_width, k)
                while k % halo:
                    halo -= 1
                self._steppers[k] = sharded_step_fn(
                    self.mesh, self.rule, steps_per_call=k, halo_width=halo
                )
            else:
                self._steppers[k] = get_model(self.rule).run(k)
        return self._steppers[k]

    # -- core loop -----------------------------------------------------------

    def advance(self, epochs: Optional[int] = None) -> int:
        """Advance by exactly ``epochs`` generations (default:
        config.max_epochs).  Observation, pacing, checkpointing, and fault
        injection happen between chunks of ``steps_per_call`` generations —
        the on-device scan in between has zero host round-trips."""
        cfg = self.config
        target = self.epoch + (epochs if epochs is not None else (cfg.max_epochs or 0))
        next_tick = time.monotonic()
        while self.epoch < target:
            if cfg.tick_s > 0:
                now = time.monotonic()
                if now < next_tick:
                    time.sleep(next_tick - now)
                next_tick = max(next_tick + cfg.tick_s, now)

            if self.injector is not None and self.injector.should_crash():
                self._crash_and_recover()

            chunk = min(cfg.steps_per_call, target - self.epoch)
            prev = self.epoch
            with profiling.annotate_epochs("advance_chunk", self.epoch):
                self.board = self._stepper(chunk)(self.board)
            self.epoch += chunk

            host_board = None
            if _crosses(prev, self.epoch, cfg.render_every) or _crosses(
                prev, self.epoch, cfg.metrics_every
            ):
                host_board = self.board_host()
                if jax.process_index() == 0:
                    self.observer.observe(self.epoch, host_board)
            if self.store is not None and _crosses(
                prev, self.epoch, cfg.checkpoint_every
            ):
                self.checkpoint(host_board)
        return self.epoch

    # -- failure & recovery --------------------------------------------------

    def _crash_and_recover(self) -> None:
        """An injected crash: in-memory state is lost; recover from the
        latest checkpoint and deterministically replay the missed epochs."""
        assert self.store is not None
        target = self.epoch
        self.crash_log.append(target)
        self.board = None  # the crash: live state gone
        ckpt = self.store.load() if self.store.latest_epoch() is not None else None
        if ckpt is None:
            self.epoch = 0
            restored = initial_board(self.config)
        else:
            self.epoch = ckpt.epoch
            restored = ckpt.board
        if self._actor_board is not None:
            # Fresh actors reseeded from the restored board (supervision
            # restart at the checkpoint, not epoch 0).
            self._actor_board = self._actor_board_cls(restored, self.rule)
            self._actor_epoch0 = self.epoch
        self.board = self._to_device(restored)
        while self.epoch < target:
            # Replay: recompute the lost epochs (deterministic rule ⇒ the
            # trajectory is bit-identical to the pre-crash one).  Reuses the
            # steps_per_call stepper so no extra compilation beyond at most
            # one partial chunk.
            chunk = min(self.config.steps_per_call, target - self.epoch)
            self.board = self._stepper(chunk)(self.board)
            self.epoch += chunk

    def checkpoint(self, host_board: Optional[np.ndarray] = None) -> None:
        if self.store is None:
            raise RuntimeError("no checkpoint_dir configured")
        if (
            self.config.checkpoint_format == "npz"
            and jax.process_count() > 1
            and jax.process_index() != 0
        ):
            # The npz store is a host-side writer: exactly one process owns
            # the file.  (The orbax store is multihost-aware — every process
            # participates in a sharded save — so it is not gated.)
            if host_board is None:
                self.board_host()  # keep the collective fetch in lockstep
            return
        if (
            host_board is None
            and jax.process_count() > 1
            and self.config.checkpoint_format == "npz"
        ):
            # npz is a host-side writer and needs the whole board; orbax
            # keeps its device-native sharded save — no cross-host gather.
            host_board = self.board_host()
        if host_board is None:
            # The store decides where the bytes come from: the orbax store
            # saves the (possibly sharded) device array without host gather;
            # the npz store gathers internally.
            host_board = self.board

        def _save():
            self.store.save(
                self.epoch,
                host_board,
                self.rule.rulestring(),
                meta={"height": self.config.height, "width": self.config.width},
            )

        if self.config.metrics_every:
            # Checkpoint cost is an operational metric: surface it alongside
            # the throughput lines.
            with profiling.timed(f"checkpoint@{self.epoch}", out=self.observer.out):
                _save()
        else:
            _save()

    def board_host(self) -> np.ndarray:
        return dist.fetch(self.board)

    def close(self) -> None:
        """Finalize: block until async checkpoint saves are durable.  Must be
        called before process exit when checkpointing is enabled — an async
        (orbax) save still in flight at interpreter shutdown is lost."""
        if self.store is not None:
            self.store.close()
        self.observer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
