"""Pallas sweep autotuner: measure (block_rows, steps_per_sweep) on device.

BASELINE.md's round-3 sweeps found the 65536² optimum (b=128, k=8) by hand;
this makes that measurement a command so other board sizes / future chips
can find theirs: time each feasible configuration on the real device and
report the best as ready-to-paste flags.  The reference has no benchmarking
machinery at all (SURVEY.md §6), so this surface is net-new capability.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

import numpy as np


def feasible(size: int, steps_per_call: int, b: int, k: int) -> bool:
    """The kernel's own feasibility rules (alignment helper imported from
    ops/pallas_stencil so this cannot silently diverge from what the kernel
    accepts): blocks tile the height, halo blocks are sublane-aligned,
    sweeps divide the chunk."""
    from akka_game_of_life_tpu.ops.pallas_stencil import _round_up8

    if k < 1 or b < 8 or b % 8:
        return False
    return size % b == 0 and b % _round_up8(k) == 0 and steps_per_call % k == 0


def sweep(
    size: int,
    *,
    steps_per_call: int = 64,
    blocks: Sequence[int] = (64, 128, 192, 256),
    sweeps: Sequence[int] = (4, 8, 16),
    timed_calls: int = 2,
    vmem_limit_mb: int = 0,
    interpret: bool = False,
    rule="conway",
) -> List[dict]:
    """Time every feasible (block_rows, steps_per_sweep) point; return one
    record per point (cells/s, seconds, or the error that disqualified it),
    best first.  A failing point (Mosaic compile error, VMEM OOM) is a
    recorded result, not a crash — exactly the shape of the round-3 manual
    sweep in BASELINE.md."""
    import jax

    from akka_game_of_life_tpu.ops.pallas_stencil import packed_multi_step_fn
    from akka_game_of_life_tpu.ops.rules import resolve_rule

    rule = resolve_rule(rule)
    rng = np.random.default_rng(0)
    if rule.kind == "ltl":
        # Dense-layout VMEM kernel (ops/pallas_ltl.py): single-generation
        # sweeps, so only block_rows varies — each (b, k) point runs k=1
        # once per block and the grid's `sweeps` axis collapses.  (LtL
        # rules ARE binary; without this branch they would fall into the
        # packed branch and fail require_packed_support on every point.)
        from akka_game_of_life_tpu.ops import pallas_ltl
        from akka_game_of_life_tpu.ops.pallas_stencil import _round_up8

        if rule.neighborhood != "box":
            raise ValueError(
                "tune supports box-neighborhood ltl rules only (the diamond "
                "has no pallas kernel)"
            )
        # Sample into a preallocated uint8 board in row chunks: a one-shot
        # rng.random((size, size)) would be 32 GiB of float64 at 65536².
        board_np = np.empty((size, size), np.uint8)
        chunk = max(1, min(size, 2**24 // size))
        for r0 in range(0, size, chunk):
            rows = min(chunk, size - r0)
            board_np[r0 : r0 + rows] = rng.random((rows, size)) < 0.4
        board = jax.device_put(board_np)
        del board_np
        hb = _round_up8(rule.radius)
        results: List[dict] = []
        for b in blocks:
            point = {"block_rows": int(b), "steps_per_sweep": 1}
            if not feasible(size, steps_per_call, b, 1) or b % hb:
                continue
            try:
                fn = pallas_ltl.ltl_pallas_multi_step_fn(
                    rule,
                    steps_per_call,
                    block_rows=b,
                    interpret=interpret,
                    vmem_limit_bytes=(
                        vmem_limit_mb * 2**20 if vmem_limit_mb else None
                    ),
                )
                out = fn(board)
                np.asarray(out[0])
                t0 = time.perf_counter()
                cur = out
                for _ in range(timed_calls):
                    cur = fn(cur)
                np.asarray(cur[0])
                dt = time.perf_counter() - t0
                point.update(
                    seconds=round(dt, 4),
                    cells_per_sec=size * size * steps_per_call * timed_calls / dt,
                )
            except Exception as e:
                point["error"] = f"{type(e).__name__}: {e}"
            results.append(point)
        results.sort(key=lambda p: p.get("cells_per_sec", -1.0), reverse=True)
        return results
    if rule.is_binary:
        # Generate the packed words directly: uniform random uint32s ARE a
        # density-1/2 random board, and 0.25 B/cell scratch (512 MiB at
        # 65536²) instead of the tens of GiB a float sample + pack would
        # cost.
        words = jax.device_put(
            rng.integers(0, 2**32, size=(size, size // 32), dtype=np.uint32)
        )

        def make_fn(b, k, vmem):
            return packed_multi_step_fn(
                rule,
                steps_per_call,
                block_rows=b,
                steps_per_sweep=k,
                interpret=interpret,
                vmem_limit_bytes=vmem,
            )

        fetch_row = lambda out: np.asarray(out[0])
    else:
        # Multi-state plane stack (Generations / wireworld): tune the plane
        # sweep (ops/pallas_gen.py) — the on-chip (b, k) data behind the
        # KERNELS.md pallas-vs-plane-scan decision (VERDICT.md round-3
        # weak #5).
        from akka_game_of_life_tpu.ops import bitpack_gen, pallas_gen

        # Pack row chunks as they are sampled so host scratch stays one
        # chunk + the plane stack (a full 65536² uint8 board would be ~4 GiB
        # before packing even starts — the blowup the binary branch's
        # direct-word sampling avoids).
        chunk = max(1, min(size, 2**27 // size))
        parts = []
        for r0 in range(0, size, chunk):
            rows = rng.integers(
                0, rule.states, size=(min(chunk, size - r0), size), dtype=np.uint8
            )
            parts.append(bitpack_gen.pack_gen_np(rows, rule.states))
        words = jax.device_put(np.concatenate(parts, axis=1))
        del parts

        def make_fn(b, k, vmem):
            return pallas_gen.gen_pallas_multi_step_fn(
                rule,
                steps_per_call,
                block_rows=b,
                steps_per_sweep=k,
                interpret=interpret,
                vmem_limit_bytes=vmem,
            )

        fetch_row = lambda out: np.asarray(out[0][0])
    results: List[dict] = []
    for b in blocks:
        for k in sweeps:
            point = {"block_rows": int(b), "steps_per_sweep": int(k)}
            if not feasible(size, steps_per_call, b, k):
                continue  # silently skip: not a failure, just not a point
            try:
                fn = make_fn(
                    b, k, vmem_limit_mb * 2**20 if vmem_limit_mb else None
                )
                out = fn(words)  # compile + warm
                fetch_row(out)  # force completion (host fetch of a row)
                t0 = time.perf_counter()
                cur = out
                for _ in range(timed_calls):
                    cur = fn(cur)
                fetch_row(cur)
                dt = time.perf_counter() - t0
                cells = size * size * steps_per_call * timed_calls
                point.update(
                    seconds=round(dt, 4),
                    cells_per_sec=cells / dt,
                )
            except Exception as e:
                point["error"] = f"{type(e).__name__}: {e}"
            results.append(point)
    results.sort(key=lambda p: p.get("cells_per_sec", -1.0), reverse=True)
    return results


def best_point(results: List[dict]) -> Optional[dict]:
    """The single winning-point selection, shared by ``best_flags`` and the
    CLI's machine-readable summary line so the two can never describe
    different points."""
    for p in results:
        if "cells_per_sec" in p:
            return p
    return None


def best_flags(results: List[dict], rule="conway") -> Optional[str]:
    """The winning point as ready-to-paste flags — only flags that actually
    drive the tuned kernel.

    Binary rules: bench.py pins both knobs (it benchmarks the binary
    Conway sweep) and `run --kernel pallas` honors block_rows.  Multi-state
    plane rules: bench.py's headline path never runs the plane sweep, so
    the flags point at `run --kernel pallas` (the gen-pallas stepper) and
    name bench_suite's gen-pallas line as the benchmark consumer.  Either
    way the product runtime auto-picks the sweep depth with a cap of
    DEFAULT_STEPS_PER_SWEEP, so a deeper winning k is flagged as
    tune/bench-only rather than silently misreported as reproducible."""
    from akka_game_of_life_tpu.ops.pallas_stencil import DEFAULT_STEPS_PER_SWEEP
    from akka_game_of_life_tpu.ops.rules import resolve_rule

    rule = resolve_rule(rule)
    p = best_point(results)
    if p is not None:
        b, k = p["block_rows"], p["steps_per_sweep"]
        if rule.kind == "ltl":
            flags = (
                f"run --kernel pallas --pallas-block-rows {b} "
                f"(benchmark line: bench_suite.bench_pallas_ltl)"
            )
        elif rule.is_binary:
            flags = (
                f"bench.py --block-rows {b} --steps-per-sweep {k}; "
                f"run --pallas-block-rows {b}"
            )
        else:
            flags = (
                f"run --kernel pallas --pallas-block-rows {b} "
                f"(benchmark line: bench_suite.bench_pallas_gen)"
            )
        if k > DEFAULT_STEPS_PER_SWEEP:
            flags += (
                f" (run auto-caps steps_per_sweep at "
                f"{DEFAULT_STEPS_PER_SWEEP}, so k={k} is tune-only)"
            )
        return flags
    return None
