"""Durable checkpoint/resume — the capability the reference only fakes.

The reference has *no* durable checkpointing: recovery replays from epoch 0
out of neighbors' unbounded in-memory histories (``CellActor.scala:34,71-74``)
and the frontend is an unrecoverable single point of failure (SURVEY.md §5).
Here a checkpoint is the full simulation state — board, epoch, rule, board
shape — written atomically (tmp + rename) so a kill at any instant leaves a
loadable latest checkpoint, meeting the north-star "glider-gun period
preserved across kill/restart" criterion.

Format: numpy .npz (the grid is uint8; a 65536² board is 4 GiB raw, so
checkpoints are np.packbits-packed for binary rules — 8 cells/byte).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import re
import tempfile
import time
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.npz$")
# Per-tile checkpoint epoch directory (streamed saves, no full-board
# assembly): ckpt_<epoch>.d/tile_<r>_<c>.npz + COMPLETE.json when durable.
_TILE_DIR_RE = re.compile(r"^ckpt_(\d+)\.d$")
_COMPLETE = "COMPLETE.json"


def _existing_format(directory: str) -> Optional[str]:
    """Detect which store format already owns a checkpoint directory."""
    d = Path(directory)
    if not d.is_dir():
        return None
    for p in d.iterdir():
        if _CKPT_RE.match(p.name) or (p.is_dir() and _TILE_DIR_RE.match(p.name)):
            return "npz"
        # An orbax step is a numeric directory carrying orbax metadata —
        # the name alone isn't enough (an unrelated output dir may contain
        # numeric subdirectories).
        if (
            p.is_dir()
            and p.name.isdigit()
            and any(
                (p / marker).exists()
                for marker in ("_CHECKPOINT_METADATA", "state", "meta")
            )
        ):
            return "orbax"
        # A crash during the very first async orbax save leaves only a
        # tmp-suffixed step dir (no finalized metadata yet); that directory
        # is still orbax-owned.
        if p.is_dir() and ".orbax-checkpoint-tmp" in p.name:
            return "orbax"
    return None


def make_store(
    directory: str, fmt: str = "npz", keep: int = 3, registry=None, tracer=None
):
    """Checkpoint store factory: ``npz`` (host, synchronous, packed) or
    ``orbax`` (device-native, async, shard-parallel).

    Refuses a directory already holding the *other* format's checkpoints —
    silently resuming from epoch 0 next to hours of foreign-format progress
    is the exact failure the checkpoint layer exists to prevent.
    """
    if fmt not in ("npz", "orbax"):
        raise ValueError(f"unknown checkpoint format {fmt!r}; use npz or orbax")
    existing = _existing_format(directory)
    if existing is not None and existing != fmt:
        raise ValueError(
            f"checkpoint dir {directory} already holds {existing}-format "
            f"checkpoints; refusing to start a {fmt}-format store there"
        )
    if fmt == "npz":
        return CheckpointStore(
            directory, keep=keep, registry=registry, tracer=tracer
        )
    from akka_game_of_life_tpu.runtime.orbax_store import OrbaxCheckpointStore

    return OrbaxCheckpointStore(
        directory, keep=keep, registry=registry, tracer=tracer
    )


class _StoreMetrics:
    """Save/restore counters + latency histograms, shared by both stores.

    The instrumentation lives in the stores (not their callers) so every
    durability path — sync saves, the async npz writer thread, orbax's
    background commit, recovery loads, the ``checkpoints`` CLI — counts
    through the same three instruments."""

    def __init__(self, registry=None, tracer=None) -> None:
        self.tracer = tracer
        if registry is None:
            from akka_game_of_life_tpu.obs import get_registry

            registry = get_registry()
        self.saves = registry.counter("gol_checkpoint_saves_total")
        self.restores = registry.counter("gol_checkpoint_restores_total")
        self._seconds = registry.histogram(
            "gol_checkpoint_seconds", labelnames=("op",)
        )
        self.save_seconds = self._seconds.labels(op="save")
        self.restore_seconds = self._seconds.labels(op="restore")

    @contextlib.contextmanager
    def timed_save(self):
        with self._span("checkpoint.save"):
            t0 = time.perf_counter()
            yield
            self.save_seconds.observe(time.perf_counter() - t0)
            self.saves.inc()

    @contextlib.contextmanager
    def timed_restore(self):
        with self._span("checkpoint.restore"):
            t0 = time.perf_counter()
            yield
            self.restore_seconds.observe(time.perf_counter() - t0)
            self.restores.inc()

    @contextlib.contextmanager
    def _span(self, name: str):
        """Every durability op is also a trace span, so checkpoint IO shows
        up on the epoch timeline.  On the sync path the thread-local stack
        parents it under the active chunk/epoch span; on the async writer
        thread it roots its own trace (still exported + flight-recorded)."""
        tracer = self.tracer
        if tracer is None:
            from akka_game_of_life_tpu.obs.tracing import get_tracer

            tracer = self.tracer = get_tracer()
        with tracer.span(name):
            yield


@dataclasses.dataclass
class Checkpoint:
    epoch: int
    board: Optional[np.ndarray]  # None only when loaded with keep_packed=True
    rule: str
    meta: dict
    # Bit-packed payload ((H, W/32) uint32 LSB-first words) when the
    # checkpoint was saved by a packed-kernel run and loaded with
    # keep_packed=True — lets a packed resume skip the O(board) host unpack.
    packed32: Optional[np.ndarray] = None


class CheckpointStore:
    """A directory of epoch-stamped checkpoints with atomic writes."""

    def __init__(
        self, directory: str, keep: int = 3, registry=None, tracer=None
    ) -> None:
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        self.metrics = _StoreMetrics(registry, tracer=tracer)

    def _write_epoch(self, epoch: int, payload: dict) -> Path:
        """Atomically write one epoch's npz (tmp + fsync + rename), then GC."""
        target = self.dir / f"ckpt_{epoch:012d}.npz"
        with self.metrics.timed_save():
            fd, tmp = tempfile.mkstemp(dir=self.dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    np.savez_compressed(f, **payload)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, target)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        self._gc()
        return target

    @staticmethod
    def _meta_blob(rule: str, meta: Optional[dict]) -> np.ndarray:
        return np.frombuffer(
            json.dumps({"rule": rule, **(meta or {})}).encode(), dtype=np.uint8
        )

    def save(
        self,
        epoch: int,
        board: np.ndarray,
        rule: str,
        meta: Optional[dict] = None,
        record_digest: bool = False,
    ) -> Path:
        board = np.asarray(board, dtype=np.uint8)
        binary = bool((board <= 1).all())
        # A durable epoch may carry its 64-bit state certificate in meta:
        # two stores (or a store and a live run) then compare by 16 hex
        # digits, never by unpacking boards (docs/OPERATIONS.md "Digest
        # certification").  Callers with a device-resident board pass the
        # digest in ``meta`` (Simulation computes it ON DEVICE for ~8
        # fetched bytes); ``record_digest=True`` is the host-side
        # convenience for embedders holding only this array — an opt-in,
        # because the host recompute is O(board) work that a flagship-size
        # save must not pay for a feature nobody enabled.
        meta = dict(meta or {})
        if record_digest and "digest" not in meta:
            from akka_game_of_life_tpu.ops import digest as odigest

            meta["digest"] = odigest.format_digest(
                odigest.value(odigest.digest_dense_np(board))
            )
        return self._write_epoch(
            epoch,
            {
                "epoch": np.int64(epoch),
                "shape": np.asarray(board.shape, dtype=np.int64),
                "packed": np.uint8(1 if binary else 0),
                "board": np.packbits(board) if binary else board,
                "meta": self._meta_blob(rule, meta),
            },
        )

    def save_packed32(
        self,
        epoch: int,
        words: np.ndarray,
        shape: Tuple[int, int],
        rule: str,
        meta: Optional[dict] = None,
        record_digest: bool = False,
    ) -> Path:
        """Save an already-bit-packed board as it arrived from the device —
        the packed-kernel runtime never unpacks on host, so a 65536²
        checkpoint transfers and writes 0.25 B/cell.  ``words`` is either
        (H, W/32) uint32 LSB-first (binary rules) or (m, H, W/32) bit planes
        (Generations rules — 0.25·m B/cell)."""
        words = np.ascontiguousarray(words, dtype=np.uint32)
        h, w = shape
        if words.ndim == 2:
            expect = (h, w // 32)
            fmt = 2  # uint32-word LSB-first layout
        else:
            # The plane count is derivable from the rule — deriving it from
            # the input would validate nothing, and a truncated plane stack
            # would silently decode to a wrong board on resume.
            from akka_game_of_life_tpu.ops.bitpack_gen import n_planes
            from akka_game_of_life_tpu.ops.rules import resolve_rule

            expect = (n_planes(resolve_rule(rule).states), h, w // 32)
            fmt = 3  # Generations bit planes, LSB plane first
        if words.shape != expect:
            raise ValueError(f"packed words {words.shape} != {expect}")
        meta = dict(meta or {})
        if record_digest and "digest" not in meta:
            # Host-side opt-in (see save()): computed straight from the
            # packed words — the packed save path never unpacks, for
            # digests either.  Device-holding callers pass meta instead.
            from akka_game_of_life_tpu.ops import digest as odigest

            lanes = (
                odigest.digest_packed_np(words, w)
                if fmt == 2
                else odigest.digest_planes_np(words, w)
            )
            meta["digest"] = odigest.format_digest(odigest.value(lanes))
        return self._write_epoch(
            epoch,
            {
                "epoch": np.int64(epoch),
                "shape": np.asarray(shape, dtype=np.int64),
                "packed": np.uint8(fmt),
                "board": words,
                "meta": self._meta_blob(rule, meta),
            },
        )

    # -- per-tile streaming saves (no full-board assembly anywhere) ----------

    def _tile_dir(self, epoch: int) -> Path:
        return self.dir / f"ckpt_{epoch:012d}.d"

    def save_tile(self, epoch: int, tile, arr) -> Path:
        """Stream one tile of epoch ``epoch`` to disk, atomically.

        ``arr`` is a uint8 tile or an already-bit-packed wire payload (the
        cluster ships tiles packed; they go to disk without a round-trip).
        Tiles arrive as workers report them; nothing holds more than one
        tile in memory and no process ever assembles the full board.  The
        epoch becomes durable (visible to ``latest_epoch``/``load``) only
        when :meth:`finalize_epoch` lands its COMPLETE marker."""
        from akka_game_of_life_tpu.runtime.wire import pack_tile

        d = self._tile_dir(epoch)
        d.mkdir(parents=True, exist_ok=True)
        payload = arr if isinstance(arr, dict) else pack_tile(
            np.asarray(arr, dtype=np.uint8)
        )
        target = d / f"tile_{int(tile[0])}_{int(tile[1])}.npz"
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez_compressed(
                    f,
                    enc=np.frombuffer(payload["enc"].encode(), dtype=np.uint8),
                    shape=np.asarray(payload["shape"], dtype=np.int64),
                    data=payload["data"],
                )
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, target)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return target

    def finalize_epoch(
        self, epoch: int, rule: str, grid, board_shape, meta: Optional[dict] = None
    ) -> None:
        """Mark a per-tile epoch durable once every tile has been saved."""
        d = self._tile_dir(epoch)
        doc = json.dumps(
            {
                "epoch": epoch,
                "rule": rule,
                "grid": list(grid),
                "shape": list(board_shape),
                **(meta or {}),
            }
        )
        # One durable save per finalized epoch (the streamed tile files are
        # its parts, not checkpoints of their own).
        with self.metrics.timed_save():
            fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    f.write(doc)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, d / _COMPLETE)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        self._gc()

    def tile_meta(self, epoch: int) -> dict:
        return json.loads((self._tile_dir(epoch) / _COMPLETE).read_text())

    def load_tile_payload(self, epoch: int, tile) -> dict:
        """The tile's wire payload exactly as stored — recovery deploys ship
        it onward without ever materializing the unpacked tile."""
        path = self._tile_dir(epoch) / f"tile_{int(tile[0])}_{int(tile[1])}.npz"
        with np.load(path) as z:
            return {
                "enc": bytes(z["enc"].tobytes()).decode(),
                "shape": [int(v) for v in z["shape"]],
                "data": z["data"].copy(),
            }

    def load_tile(self, epoch: int, tile) -> np.ndarray:
        from akka_game_of_life_tpu.runtime.wire import unpack_tile

        return unpack_tile(self.load_tile_payload(epoch, tile))

    def tile_digest(self, epoch: int) -> int:
        """Recompute a per-tile epoch's merged 64-bit digest — one tile in
        memory at a time, the board never assembled (the validation path
        behind ``checkpoints --validate``'s tile-dir branch; the frontend's
        recovery-source certification is its payload-level twin).  Also
        verifies every tile decodes to the layout's shape — a truncated or
        mis-shaped tile raises ValueError rather than digesting garbage."""
        from akka_game_of_life_tpu.ops import digest as odigest

        meta = self.tile_meta(epoch)
        rows, cols = meta["grid"]
        h, w = (int(v) for v in meta["shape"])
        th, tw = h // rows, w // cols

        def tile_lanes(i: int, j: int) -> np.ndarray:
            tile = self.load_tile(epoch, (i, j))
            if tile.shape != (th, tw):
                raise ValueError(
                    f"tile ({i}, {j}) of epoch {epoch} has shape "
                    f"{tile.shape}, layout expects {(th, tw)}"
                )
            return odigest.digest_dense_np(tile, (i * th, j * tw), w)

        lanes = odigest.merge_lanes(
            tile_lanes(i, j) for i in range(rows) for j in range(cols)
        )
        return odigest.value(lanes)

    def _epochs(self):
        """(epoch, path) of every durable checkpoint — full-board files and
        COMPLETE-marked tile dirs — sorted by epoch."""
        out = []
        for p in self.dir.iterdir():
            m = _CKPT_RE.match(p.name)
            if m:
                out.append((int(m.group(1)), p))
                continue
            m = _TILE_DIR_RE.match(p.name)
            if m and p.is_dir() and (p / _COMPLETE).exists():
                out.append((int(m.group(1)), p))
        return sorted(out)

    def _gc(self) -> None:
        import shutil

        epochs = self._epochs()
        for _, p in epochs[: max(0, len(epochs) - self.keep)]:
            if p.is_dir():
                shutil.rmtree(p, ignore_errors=True)
            else:
                p.unlink(missing_ok=True)
        # Unfinalized tile dirs older than the newest durable epoch are
        # failed partial saves; sweep them.
        if epochs:
            newest = epochs[-1][0]
            for p in self.dir.iterdir():
                m = _TILE_DIR_RE.match(p.name)
                if (
                    m
                    and p.is_dir()
                    and not (p / _COMPLETE).exists()
                    and int(m.group(1)) < newest
                ):
                    shutil.rmtree(p, ignore_errors=True)

    def latest_epoch(self) -> Optional[int]:
        epochs = self._epochs()
        return epochs[-1][0] if epochs else None

    def load(
        self, epoch: Optional[int] = None, *, keep_packed: bool = False
    ) -> Checkpoint:
        """Load a checkpoint.  With ``keep_packed=True`` a packed32-format
        checkpoint comes back with ``packed32`` set and ``board=None`` — the
        packed-kernel resume path pushes the words straight to device."""
        with self.metrics.timed_restore():
            return self._load(epoch, keep_packed=keep_packed)

    def _load(
        self, epoch: Optional[int] = None, *, keep_packed: bool = False
    ) -> Checkpoint:
        epochs = self._epochs()
        if not epochs:
            raise FileNotFoundError(f"no checkpoints in {self.dir}")
        if epoch is None:
            epoch, path = epochs[-1]
        else:
            matches = [p for e, p in epochs if e == epoch]
            if not matches:
                raise FileNotFoundError(f"no checkpoint for epoch {epoch} in {self.dir}")
            path = matches[0]
        if path.is_dir():
            # Per-tile epoch: stitch on demand (small boards / tests; the
            # cluster frontend deploys tile-by-tile via load_tile instead).
            meta = self.tile_meta(epoch)
            rows, cols = meta["grid"]
            shape = tuple(int(v) for v in meta["shape"])
            th, tw = shape[0] // rows, shape[1] // cols
            board = np.empty(shape, dtype=np.uint8)
            for i in range(rows):
                for j in range(cols):
                    board[i * th : (i + 1) * th, j * tw : (j + 1) * tw] = (
                        self.load_tile(epoch, (i, j))
                    )
            rule = meta.pop("rule")
            extra = {
                k: v for k, v in meta.items() if k not in ("epoch", "grid", "shape")
            }
            return Checkpoint(epoch=int(epoch), board=board, rule=rule, meta=extra)
        with np.load(path) as z:
            shape: Tuple[int, ...] = tuple(int(v) for v in z["shape"])
            meta = json.loads(bytes(z["meta"].tobytes()).decode())
            fmt = int(z["packed"])
            if fmt in (2, 3):  # uint32 words / Generations bit planes
                words = z["board"].copy()
                rule = meta.pop("rule")
                if keep_packed:
                    return Checkpoint(
                        epoch=int(epoch),
                        board=None,
                        rule=rule,
                        meta=meta,
                        packed32=words,
                    )
                if fmt == 3:
                    from akka_game_of_life_tpu.ops.bitpack_gen import unpack_gen_np

                    board = unpack_gen_np(words).reshape(shape)
                else:
                    from akka_game_of_life_tpu.ops.bitpack import unpack_np

                    board = unpack_np(words).reshape(shape)
                return Checkpoint(
                    epoch=int(epoch), board=board, rule=rule, meta=meta
                )
            if fmt:
                n = int(np.prod(shape))
                board = np.unpackbits(z["board"], count=n).reshape(shape)
            else:
                board = z["board"].reshape(shape)
        rule = meta.pop("rule")
        return Checkpoint(
            epoch=int(epoch), board=board.astype(np.uint8), rule=rule, meta=meta
        )

    def wait(self) -> None:
        """Saves are synchronous; nothing to wait for (orbax-store parity)."""

    def close(self) -> None:
        """No resources held (orbax-store parity — callers can close
        unconditionally)."""


_LAYOUTS = {0: "dense-uint8", 1: "packbits", 2: "packed32", 3: "gen-planes"}


def describe_store(directory: str, validate: bool = False):
    """Inspect a checkpoint directory (either format) without a Simulation.

    Yields one dict per durable epoch — epoch, store format, layout, rule,
    shape, bytes on disk (tile epochs add grid/tile counts).  With
    ``validate=True`` each epoch is additionally loaded in full and
    ``ok``/``error`` reported.  The reference has no durable state to
    inspect at all (its recovery log is in-memory actor histories,
    ``CellActor.scala:34``); this is the operator's view of ours.
    """
    fmt = _existing_format(directory)
    if fmt is None:
        return
    if fmt == "orbax":
        from akka_game_of_life_tpu.runtime.orbax_store import OrbaxCheckpointStore

        store = OrbaxCheckpointStore(directory)
        try:
            for epoch in store.epochs():
                info = {"epoch": epoch, "store": "orbax", "layout": "device-native"}
                step_dir = Path(directory) / str(epoch)
                if step_dir.is_dir():
                    info["bytes"] = sum(
                        p.stat().st_size for p in step_dir.rglob("*") if p.is_file()
                    )
                # Orbax has no cheap metadata-only read for our composite,
                # so rule/shape always come from a full restore — these are
                # operator inspections, not a hot path.
                try:
                    ck = store.load(epoch)
                    info.update(rule=ck.rule, shape=list(np.shape(ck.board)))
                    if validate:
                        info["ok"] = True
                except Exception as e:  # surfaced per-epoch, not fatal
                    info["error"] = f"{type(e).__name__}: {e}"
                    if validate:
                        info["ok"] = False
                yield info
        finally:
            store.close()
        return
    store = CheckpointStore(directory)
    for epoch, path in store._epochs():
        info = {"epoch": epoch, "store": "npz"}
        try:
            if path.is_dir():
                meta = store.tile_meta(epoch)
                tiles = sorted(path.glob("tile_*.npz"))
                info.update(
                    layout="tiles",
                    rule=meta.get("rule"),
                    shape=meta.get("shape"),
                    grid=meta.get("grid"),
                    tiles=len(tiles),
                    bytes=sum(t.stat().st_size for t in tiles),
                )
                if meta.get("digest"):
                    info["digest"] = meta["digest"]
            else:
                with np.load(path) as z:
                    meta = json.loads(bytes(z["meta"].tobytes()).decode())
                    code = int(z["packed"])
                    info.update(
                        layout=_LAYOUTS.get(code, f"format-{code}"),
                        rule=meta.get("rule"),
                        shape=[int(v) for v in z["shape"]],
                        bytes=path.stat().st_size,
                    )
                    if meta.get("digest"):
                        # The recorded state certificate: two stores (an
                        # A/B pair, a live run's metrics line) compare by
                        # this field alone — no tile unpacking, no board
                        # fetch.
                        info["digest"] = meta["digest"]
        except Exception as e:
            # Unreadable metadata is itself a finding, not a crash.
            info.update(error=f"{type(e).__name__}: {e}")
            if validate:
                info["ok"] = False
            yield info
            continue
        if validate:
            try:
                from akka_game_of_life_tpu.ops import digest as odigest

                recorded = info.get("digest")
                computed = None
                if path.is_dir():
                    # Per-tile epochs validate tile-by-tile: every tile is
                    # read, shape-checked, and digested with its global
                    # origin — the board is NEVER assembled (exactly the
                    # no-assembly discipline the digest plane exists for;
                    # the old path stitched a 65536²-class board here).
                    computed = odigest.format_digest(store.tile_digest(epoch))
                    info["ok"] = True
                else:
                    # Packed epochs validate in packed form: keep_packed
                    # skips the O(board) host unpack, so a 65536² packed32
                    # checkpoint validates through its 512 MiB of words,
                    # not 4 GiB of cells.
                    ck = store.load(epoch, keep_packed=True)
                    if ck.packed32 is not None:
                        shape = info.get("shape")
                        h, words = (
                            ck.packed32.shape[-2],
                            ck.packed32.shape[-1],
                        )
                        info["ok"] = shape is None or (
                            list(shape) == [h, words * 32]
                        )
                    else:
                        info["ok"] = ck.board is not None and list(
                            ck.board.shape
                        ) == list(info.get("shape") or ck.board.shape)
                    if recorded is not None and info["ok"]:
                        # Re-derive the certificate from the payload on
                        # disk: a bit flip anywhere in the board surfaces
                        # here, which a shape check can never see.
                        if ck.packed32 is not None:
                            w = int(info["shape"][1])
                            lanes = (
                                odigest.digest_packed_np(ck.packed32, w)
                                if ck.packed32.ndim == 2
                                else odigest.digest_planes_np(ck.packed32, w)
                            )
                            computed = odigest.format_digest(
                                odigest.value(lanes)
                            )
                        else:
                            computed = odigest.format_digest(
                                odigest.value(
                                    odigest.digest_dense_np(ck.board)
                                )
                            )
                if recorded is not None and computed is not None:
                    info["digest_ok"] = computed == recorded
                    if not info["digest_ok"]:
                        info["ok"] = False
                        info["error"] = (
                            f"digest mismatch: stored {recorded}, "
                            f"computed {computed}"
                        )
            except Exception as e:
                info.update(ok=False, error=f"{type(e).__name__}: {e}")
        yield info
