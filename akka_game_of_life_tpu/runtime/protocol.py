"""Control-plane message protocol — the reference's actor messages, as data.

This is the plugin boundary SURVEY.md §2 calls out: the ``Tick``/``CellState``
message contract between coordinator and compute backends, preserved so the
CPU per-cell backend and the TPU stencil backend are swappable by role
config.  Mapping to the reference protocol:

==========================  ====================================================
This protocol               Reference message (file:line)
==========================  ====================================================
REGISTER / WELCOME          cluster join + MemberUp (BoardCreator.scala:125-126)
HEARTBEAT                   cluster gossip liveness (application.conf:23)
DEPLOY                      remote CellActor deployment (BoardCreator.scala:65-70)
OWNERS                      NeighboursRefs wiring + re-wiring — who serves each
                            tile, with peer addresses
                            (BoardCreator.scala:86-88,149-151)
TICK                        CurrentEpochMsg broadcast (BoardCreator.scala:113-116)
PROGRESS                    a cell's state landing in History, as a control
                            ping only — the data rides peer-to-peer
                            (CellActor.scala:81)
PEER_RING (worker↔worker)   neighbor state push between cells — direct, no
                            coordinator relay (NextStateCellGathererActor:32-36)
PEER_RING_BATCH             (new) every ring bound for one peer in an
                            epoch/chunk, coalesced into a single frame
                            (bit-packed entries for binary rules); PEER_PULL
                            replies ride the same frame kind
PEER_PULL (worker↔worker)   GetStateFromEpoch re-ask to a specific neighbor
                            (NextStateCellGathererActor.scala:49-53); carries
                            every missing tile of that owner in one frame
PRUNE                       (new) bounded-history floor broadcast
TILE_STATE                  CellStateMsg to the logger (BoardCreator.scala:159)
CRASH / CRASH_TILE          DoCrashMsg fault injection (CellActor.scala:53-55)
REDEPLOY_REQUEST            postRestart → SendMeMyNeighbours (CellActor.scala:21-25)
GATHER_FAILED               FailedToGatherInfoMsg — gatherer gives up, parent
                            repairs the neighborhood
                            (NextStateCellGathererActor.scala:49-58)
PAUSE / RESUME              PauseSimulation/ResumeSimulation — *dead code* in
                            the reference (BoardCreator.scala:109-112); reachable here
SHUTDOWN                    (new) orderly termination
GOODBYE                     graceful leave (cluster down)
MIGRATE_PREPARE /           (new) the elastic plane: live tile migration —
MIGRATE_STATE /             freeze a tile at its chunk boundary, ship its
MIGRATE_ABORT               packed state + digest lanes to the frontend,
                            certify on arrival, commit via an OWNERS
                            rewiring (or roll back loudly); the reference
                            can only *react* to failure, never move load
DRAIN_REQUEST /             (new) graceful scale-in: a worker asks to leave,
DRAIN_COMPLETE              its tiles migrate off live, and only then is it
                            released — planned departure never trips the
                            node-loss redeploy path
SERVE_OPS / SERVE_RESULT    (new) cluster-sharded serving: coalesced session
                            ops (create/step/delete/get/adopt/step_raw) from
                            the frontend's tenant surface to one worker's
                            batch engine, and the coalesced results back
SHARD_PREPARE /             (new) session-shard migration: freeze a shard's
SHARD_STATE /               sessions on the source, ship them digest-
SHARD_COMMIT / SHARD_ABORT  certified, commit ownership (or roll back) —
                            the tile-migration protocol, session-shaped
SHARD_REPLICATE /           (new) session replication: a shard primary
SHARD_REPLICATE_ACK         streams dirty session snapshots (bit-packed +
                            digest lanes) to the frontend, which relays
                            them to the shard's replica worker through its
                            op FIFO and acks the primary with the per-
                            session epoch watermark (or parks/resets the
                            stream) — promotion on worker loss resumes
                            from the last acked state
COST                        (new) compile & device-cost observatory: a
                            worker's low-cadence ledger frame — per-
                            family program counts/compile bill/priced
                            throughput plus device-memory watermarks —
                            merged by the frontend into /programs,
                            /cost, and /healthz (obs/programs.py)
PROFILE                     (new) on-demand profiler fan-out: the
                            frontend relays one POST /profile capture
                            request to every worker so a single call
                            profiles the whole cluster window
TILED_HALO /                (new) worker-resident tiled sessions: one
TILED_HALO_ACK              chunk's O(perimeter) edge strip for a
                            neighbor chunk at an epoch barrier, shipped
                            worker-to-worker over the peer data plane and
                            enqueued onto the receiver's serve op FIFO —
                            the frontend never touches per-round cell
                            state; the ack clears the sender's
                            retransmit buffer
P_HELLO                     (new) frontend federation: first frame on a
                            freshly dialed peer link — name, advertised
                            addresses, incarnation — answered with the
                            receiver's own hello (the Akka Cluster seed
                            handshake, application.conf:7-12)
P_GOSSIP                    (new) frontend federation: heartbeat-aged
                            membership + slice-table deltas (LWW by
                            version) + cluster-budget shares, the
                            convergence vehicle (application.conf:23-26)
P_FWD_OPS /                 (new) frontend federation: serve ops for a
P_FWD_RESULT                foreign slice forwarded to the owning
                            frontend over the peer link (per-peer FIFO,
                            executed in arrival order on the owner) and
                            the coalesced results back
P_REPLICATE /               (new) frontend federation: a frontend's
P_REPLICATE_ACK             slice of control state — session index
                            rows, replication watermarks, certified
                            floors — streamed to its standby peer with
                            the PR 14 seq/ack watermark discipline, so
                            a SIGKILLed frontend's slice promotes from
                            the last acked row set
SHARD_HOME                  (new) worker → frontend after a control-
                            channel re-home: the shards + session truth
                            this worker hosts, so the adopting frontend
                            replaces promoted placeholder rows with
                            worker truth and clears the failover window
FED_PEERS                   (new) frontend → worker whenever the
                            federation peer set changes: the live peer
                            frontends' cluster addresses, the fallback
                            list a worker re-homes its control channel
                            to after a frontend loss
==========================  ====================================================

Every message constant below must appear in docs/OPERATIONS.md's
"Protocol messages" table — ``tools/check_protocol_msgs.py`` (tier-1, via
``tests/test_rebalance.py``) lint-enforces it, so new messages cannot ship
undocumented.

Wire form: each message is a JSON object with a ``type`` field from the
constants below; numpy arrays ride as base64 (see :mod:`wire`).

Trace propagation: frontend→backend envelopes (TICK, DEPLOY, CRASH,
CRASH_TILE) may carry the sender's span context under
:data:`akka_game_of_life_tpu.obs.tracing.TRACE_KEY` (attached by
``wire.attach_trace``), so a worker's step/halo/recovery spans become
children of the frontend epoch span that caused them.  The key is
underscored — it can never collide with a payload field — and decoders
that ignore it lose nothing but causality.

The serve plane extends the same discipline to its protocol
(``serve_trace``, on by default): each op inside a ``SERVE_OPS`` frame
carries the ``serve.request`` ctx of the HTTP request that caused it
(the frame itself carries the first traced op's ctx — one frame
coalesces many requests), the worker opens its ``serve.batch`` span as
that ctx's child and echoes the ctx on the matching ``serve_result``
entry, and ``shard_*``/``replicate`` control frames join whatever span
is active at enqueue time (a promotion, a migration) so failover
machinery traces under the event that triggered it.
"""

from __future__ import annotations

# backend → frontend
REGISTER = "register"
HEARTBEAT = "heartbeat"
PROGRESS = "progress"
TILE_STATE = "tile_state"
REDEPLOY_REQUEST = "redeploy_request"
GATHER_FAILED = "gather_failed"
GOODBYE = "goodbye"
# (new) batched finished trace spans, so the frontend's --trace-file /
# /trace holds the whole cluster's causal timeline in one document (the
# multi-process CLI roles forward; the in-process harness shares a tracer
# and never needs to)
SPANS = "spans"
# (new) compile & device-cost observatory: the worker's low-cadence
# program-ledger + device-watermark frame (obs/programs.py summary())
COST = "cost"

# frontend → backend
WELCOME = "welcome"
DEPLOY = "deploy"
OWNERS = "owners"
TICK = "tick"
PRUNE = "prune"
CRASH = "crash"
CRASH_TILE = "crash_tile"
PAUSE = "pause"
RESUME = "resume"
SHUTDOWN = "shutdown"
# (new) on-demand cluster profiler capture fan-out (POST /profile)
PROFILE = "profile"

# elastic plane: live tile migration + graceful drain
# frontend → backend
MIGRATE_PREPARE = "migrate_prepare"
MIGRATE_ABORT = "migrate_abort"
DRAIN_COMPLETE = "drain_complete"
# backend → frontend
MIGRATE_STATE = "migrate_state"
DRAIN_REQUEST = "drain_request"

# cluster-sharded serving plane: the session router as the frontend's
# tenant-facing surface, with per-worker vmapped batch engines behind it
# frontend → worker
SERVE_OPS = "serve_ops"
SHARD_PREPARE = "shard_prepare"
SHARD_COMMIT = "shard_commit"
SHARD_ABORT = "shard_abort"
# session replication: ack-watermark half (frontend → primary, on the
# per-worker op FIFO so it can never reorder against shard control)
SHARD_REPLICATE_ACK = "shard_replicate_ack"
# worker → frontend
SERVE_RESULT = "serve_result"
SHARD_STATE = "shard_state"
# session replication: data half (primary → frontend, relayed to the
# shard's replica as a ``replicate`` op on the replica's op FIFO)
SHARD_REPLICATE = "shard_replicate"

# worker ↔ worker (the peer-to-peer data plane)
PEER_HELLO = "peer_hello"
PEER_RING = "peer_ring"
PEER_RING_BATCH = "peer_ring_batch"
PEER_PULL = "peer_pull"
# worker ↔ worker: resident tiled-session halo exchange (received frames
# ride the serve plane's per-worker op FIFO, so halo installs order
# against chunk installs/steps/migrations like every other serve op)
TILED_HALO = "tiled_halo"
TILED_HALO_ACK = "tiled_halo_ack"

# frontend ↔ frontend (the federation peer plane): gossip-converged
# membership, slice-table deltas, forwarded serve ops, and control-state
# replication between frontends — all on one per-peer FIFO link so
# forwarded ops can never reorder against the slice-ownership control
# frames that route them
P_HELLO = "p_hello"
P_GOSSIP = "p_gossip"
P_FWD_OPS = "p_fwd_ops"
P_FWD_RESULT = "p_fwd_result"
P_REPLICATE = "p_replicate"
P_REPLICATE_ACK = "p_replicate_ack"

# worker → frontend: control-channel re-home announcement — after a
# frontend loss the worker reconnects to a surviving peer and declares
# the shards/sessions it hosts, which closes that slice's failover window
SHARD_HOME = "shard_home"

# frontend → worker: the live federation peers' cluster addresses (sent
# in WELCOME and re-pushed whenever the peer set changes), the fallback
# list the worker's control channel re-homes to after a frontend loss
FED_PEERS = "fed_peers"
