"""Cluster membership — the Akka Cluster gossip/DeathWatch capability.

The reference gets membership from Akka Cluster: gossip with a seed node,
``MemberUp``/``MemberRemoved`` events, aggressive 1-second auto-down
(``application.conf:19-23``), plus per-actor DeathWatch
(``BoardCreator.scala:83,120-121``).  Here the frontend *is* the seed node;
workers register over TCP and heartbeat; a member is evicted when its
connection drops (DeathWatch) or its heartbeat goes stale past
``failure_timeout_s`` (auto-down).  Same two failure detectors, one registry.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from akka_game_of_life_tpu.runtime.tiles import TileId


@dataclasses.dataclass
class Member:
    name: str
    channel: object  # wire.Channel
    last_seen: float
    tiles: List[TileId] = dataclasses.field(default_factory=list)
    alive: bool = True
    # Peer-to-peer data-plane address (host as seen by the frontend, the
    # worker's advertised peer listener port) — brokered to other workers
    # via OWNERS; the frontend itself never carries ring bytes.
    peer_host: str = ""
    peer_port: int = 0
    # Graceful scale-in: a draining member still serves everything it owns
    # but receives no NEW tiles (placement, recovery, or migration) while
    # the elastic plane moves its tiles off; drain_acked marks the one
    # DRAIN_COMPLETE release already sent.
    draining: bool = False
    drain_acked: bool = False


class Membership:
    """Thread-safe member registry with heartbeat-based failure detection."""

    def __init__(self, failure_timeout_s: float) -> None:
        self.failure_timeout_s = failure_timeout_s
        self._members: Dict[str, Member] = {}
        self._lock = threading.RLock()
        self._seq = 0

    def register(
        self,
        channel,
        name: Optional[str] = None,
        peer_host: str = "",
        peer_port: int = 0,
    ) -> Member:
        with self._lock:
            self._seq += 1
            if not name:
                name = f"backend-{self._seq}"
            if name in self._members and self._members[name].alive:
                name = f"{name}-{self._seq}"
            m = Member(
                name=name,
                channel=channel,
                last_seen=time.monotonic(),
                peer_host=peer_host,
                peer_port=peer_port,
            )
            self._members[name] = m
            return m

    def beat(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.last_seen = time.monotonic()

    def get(self, name: str) -> Optional[Member]:
        with self._lock:
            return self._members.get(name)

    def alive_members(self) -> List[Member]:
        with self._lock:
            return [m for m in self._members.values() if m.alive]

    def placeable_members(self) -> List[Member]:
        """Members that may RECEIVE tiles: alive and not draining.  Every
        placement decision (initial deal, node-loss reassignment, migration
        destination) filters through this — a worker mid-drain must never
        be handed new work it would immediately have to hand back."""
        with self._lock:
            return [
                m for m in self._members.values() if m.alive and not m.draining
            ]

    def mark_dead(self, name: str) -> Optional[Member]:
        """DeathWatch fired (EOF) or auto-down (stale heartbeat)."""
        with self._lock:
            m = self._members.get(name)
            if m is None or not m.alive:
                return None
            m.alive = False
            return m

    def stale_members(self, now: Optional[float] = None) -> List[Member]:
        now = now if now is not None else time.monotonic()
        with self._lock:
            return [
                m
                for m in self._members.values()
                if m.alive and (now - m.last_seen) > self.failure_timeout_s
            ]
