"""Per-cell actor engine — the reference's architecture, preserved as the
CPU parity backend (BASELINE.json config 1).

This is a faithful in-process re-expression of the reference's compute layer
(``CellActor.scala`` + ``NextStateCellGathererActor.scala``), kept because it
*is* the reference's distinctive design and serves as the semantic oracle for
the async/recovery behaviors the TPU path re-implements densely:

- one :class:`Cell` per grid position holding an epoch-keyed state history
  seeded ``{0: initial}`` (``CellActor.scala:34``);
- cells advance lazily toward the announced epoch, one step at a time, gated
  by a ``waiting`` latch (``scheduleTransitionToNextepochIfNeeded``,
  ``CellActor.scala:41-47``);
- each step spawns a :class:`Gatherer` that asks all 8 neighbors for their
  state at the cell's epoch (``NextStateCellGathererActor.scala:32-36``);
- a neighbor serves the request from history, or **queues** it when asked for
  an epoch it hasn't computed (``CellActor.scala:71-77``), flushing on state
  set (``:82-88``);
- a crashed cell resets to epoch 0 and replays forward out of its neighbors'
  histories (``§3.3`` in SURVEY.md) — the unbounded history *is* the recovery
  log, exactly as in the reference.

Differences from the reference, by design: the transition rule is a correct
parameterized B/S rule (not the ``:44`` bug), the board is toroidal (not
edge-clipped), and message delivery is a deterministic FIFO event loop (akka
delivery order within a pair is FIFO too; there is no network loss in
process, so the gatherer's retry path is unnecessary).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule

Position = Tuple[int, int]


class Gatherer:
    """Per-step neighbor-state collector + rule kernel
    (``NextStateCellGathererActor``)."""

    __slots__ = ("cell", "epoch", "neighbors", "want", "got", "current_state")

    def __init__(self, cell: "Cell", epoch: int, neighbors: List[Position]) -> None:
        self.cell = cell
        self.epoch = epoch
        # On toruses smaller than 3 a neighbor position repeats; keep the
        # full offset list so counting uses multiplicity, matching the dense
        # stencil kernels (one reply per *distinct* position still suffices).
        self.neighbors = list(neighbors)
        self.want = set(neighbors)
        self.got: Dict[Position, int] = {}
        self.current_state = cell.history[epoch]

    def offer(self, pos: Position, state: int) -> bool:
        """Accumulate one reply (set semantics: duplicates are no-ops,
        ``GatheredData``'s dedup).  Returns True when complete."""
        if pos in self.want:
            self.got[pos] = state
        return len(self.got) == len(self.want)

    def result(self, rule: Rule) -> int:
        alive = sum(1 for p in self.neighbors if self.got[p] == 1)
        if not rule.is_totalistic:  # wireworld: see ops/stencil.apply_rule
            if self.current_state == 1:
                return 2
            if self.current_state == 2:
                return 3
            if self.current_state == 3 and (rule.birth_mask >> alive) & 1:
                return 1
            return self.current_state
        mask = rule.survive_mask if self.current_state == 1 else rule.birth_mask
        if rule.is_binary:
            return (mask >> alive) & 1
        if self.current_state == 0:
            return (rule.birth_mask >> alive) & 1
        if self.current_state == 1:
            return 1 if (rule.survive_mask >> alive) & 1 else (2 % rule.states)
        return (self.current_state + 1) % rule.states


class Cell:
    """One grid cell: epoch-keyed history + request queue (``CellActor``)."""

    __slots__ = ("pos", "history", "queued_requests", "waiting", "initial")

    def __init__(self, pos: Position, initial: int) -> None:
        self.pos = pos
        self.initial = initial
        self.history: Dict[int, int] = {0: initial}  # the History map
        # requests for epochs not yet computed: epoch -> [gatherer ids]
        self.queued_requests: Dict[int, List[int]] = {}
        self.waiting = False  # waitingForNewState latch

    @property
    def epoch(self) -> int:
        return max(self.history)

    def crash(self) -> None:
        """Supervision restart: vars reinitialized, history lost
        (``CellActor.scala:32-36``)."""
        self.history = {0: self.initial}
        self.queued_requests = {}
        self.waiting = False


class ActorBoard:
    """A toroidal board of per-cell actors with a deterministic FIFO mailbox.

    The coordinator role (epoch announcements, crash injection) is the caller;
    ``advance_to`` is the ``CurrentEpochMsg`` broadcast plus event-loop drain.
    """

    def __init__(self, board: np.ndarray, rule) -> None:
        self.rule = resolve_rule(rule)
        if self.rule.radius != 1:
            raise ValueError(
                "the per-cell actor engine is Moore-8 (radius 1); "
                "radius-R ltl rules run on the dense kernel"
            )
        board = np.asarray(board, dtype=np.uint8)
        self.shape = board.shape
        h, w = self.shape
        self.cells: Dict[Position, Cell] = {
            (y, x): Cell((y, x), int(board[y, x])) for y in range(h) for x in range(w)
        }
        self._neighbors: Dict[Position, List[Position]] = {
            pos: self._moore(pos) for pos in self.cells
        }
        self._gatherers: Dict[int, Gatherer] = {}
        self._next_gid = 0
        self._mailbox: Deque[tuple] = deque()
        self.global_epoch = 0
        self.messages_processed = 0

    def _moore(self, pos: Position) -> List[Position]:
        h, w = self.shape
        y, x = pos
        return [
            ((y + dy) % h, (x + dx) % w)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        ]

    # -- coordinator API -----------------------------------------------------

    def advance_to(self, target_epoch: int) -> None:
        """Announce the epoch and drain the event loop until every cell has
        caught up (cells fast-forward one epoch at a time, as in
        ``CellActor.scala:86``)."""
        self.global_epoch = max(self.global_epoch, target_epoch)
        for pos in self.cells:
            self._mailbox.append(("current_epoch", pos))
        self._drain()

    def crash_cell(self, pos: Position) -> None:
        """DoCrashMsg: the cell loses all state and replays from epoch 0 via
        its neighbors' histories."""
        self.cells[pos].crash()
        # postRestart → re-announce the epoch so it starts catching up
        self._mailbox.append(("current_epoch", pos))
        self._drain()

    def board_at_current(self) -> np.ndarray:
        out = np.zeros(self.shape, dtype=np.uint8)
        for (y, x), cell in self.cells.items():
            out[y, x] = cell.history[cell.epoch]
        return out

    def min_epoch(self) -> int:
        return min(c.epoch for c in self.cells.values())

    def prune_histories_below(self, epoch: int) -> None:
        """Optional bounded-history mode (the reference never prunes —
        SURVEY.md §2 bug 5; pruning trades replay depth for memory)."""
        for cell in self.cells.values():
            keep = {e: s for e, s in cell.history.items() if e >= epoch}
            if not keep:
                keep = {cell.epoch: cell.history[cell.epoch]}
            cell.history = keep

    # -- event loop ----------------------------------------------------------

    def _drain(self) -> None:
        while self._mailbox:
            msg = self._mailbox.popleft()
            self.messages_processed += 1
            kind = msg[0]
            if kind == "current_epoch":
                self._on_current_epoch(msg[1])
            elif kind == "get_to_next_epoch":
                self._on_get_to_next_epoch(msg[1])
            elif kind == "get_state":
                _, requester_gid, pos, epoch = msg
                self._on_get_state(requester_gid, pos, epoch)
            elif kind == "state_reply":
                _, gid, pos, state = msg
                self._on_state_reply(gid, pos, state)
            elif kind == "set_state":
                _, pos, epoch, state = msg
                self._on_set_state(pos, epoch, state)

    def _on_current_epoch(self, pos: Position) -> None:
        # scheduleTransitionToNextepochIfNeeded (CellActor.scala:41-47)
        cell = self.cells[pos]
        if cell.epoch < self.global_epoch and not cell.waiting:
            cell.waiting = True
            self._mailbox.append(("get_to_next_epoch", pos))

    def _on_get_to_next_epoch(self, pos: Position) -> None:
        # spawn a gatherer child (CellActor.scala:67-69)
        cell = self.cells[pos]
        gid = self._next_gid
        self._next_gid += 1
        g = Gatherer(cell, cell.epoch, self._neighbors[pos])
        self._gatherers[gid] = g
        for npos in g.want:
            self._mailbox.append(("get_state", gid, npos, g.epoch))

    def _on_get_state(self, requester_gid: int, pos: Position, epoch: int) -> None:
        # GetStateFromEpoch: serve from history or queue (CellActor.scala:71-77)
        cell = self.cells[pos]
        if epoch in cell.history:
            self._mailbox.append(
                ("state_reply", requester_gid, pos, cell.history[epoch])
            )
        else:
            cell.queued_requests.setdefault(epoch, []).append(requester_gid)

    def _on_state_reply(self, gid: int, pos: Position, state: int) -> None:
        g = self._gatherers.get(gid)
        if g is None:
            return
        if g.offer(pos, state):
            new_state = g.result(self.rule)
            del self._gatherers[gid]
            self._mailbox.append(("set_state", g.cell.pos, g.epoch + 1, new_state))

    def _on_set_state(self, pos: Position, epoch: int, state: int) -> None:
        # SetNewStateMsg guard: previous epoch must exist (CellActor.scala:29-30,79)
        cell = self.cells[pos]
        if epoch - 1 not in cell.history:
            return
        cell.history[epoch] = state
        cell.waiting = False
        # flush queued requests for this epoch (CellActor.scala:82-88)
        for gid in cell.queued_requests.pop(epoch, []):
            self._mailbox.append(("state_reply", gid, pos, state))
        # immediately reschedule if still behind (CellActor.scala:86)
        self._mailbox.append(("current_epoch", pos))


class _TileActorBoard(ActorBoard):
    """An ActorBoard over one tile whose out-of-bounds Moore neighbors are
    *ghost cells* — stand-ins for remote cells, fed per epoch from the halo
    the control plane delivers.  This is how the per-cell-actor architecture
    participates in the tiled cluster: the same pull/queue semantics, with
    the halo as the remote neighbors' served history."""

    def __init__(self, board: np.ndarray, rule) -> None:
        h, w = board.shape
        self.ghost_cells: Dict[Position, Cell] = {}
        for y in range(-1, h + 1):
            for x in range(-1, w + 1):
                if 0 <= y < h and 0 <= x < w:
                    continue
                g = Cell((y, x), 0)
                g.history = {}  # no epoch served until a halo feeds it
                self.ghost_cells[(y, x)] = g
        super().__init__(board, rule)

    def _moore(self, pos: Position) -> List[Position]:
        y, x = pos
        return [
            (y + dy, x + dx)
            for dy in (-1, 0, 1)
            for dx in (-1, 0, 1)
            if (dy, dx) != (0, 0)
        ]

    def _on_get_state(self, requester_gid: int, pos: Position, epoch: int) -> None:
        ghost = self.ghost_cells.get(pos)
        if ghost is None:
            super()._on_get_state(requester_gid, pos, epoch)
            return
        if epoch in ghost.history:
            self._mailbox.append(("state_reply", requester_gid, pos, ghost.history[epoch]))
        else:
            ghost.queued_requests.setdefault(epoch, []).append(requester_gid)

    def feed_halo(self, epoch: int, padded: np.ndarray) -> None:
        """Publish the remote ring's states for ``epoch`` into the ghosts
        (and flush any queued requests waiting on them)."""
        h, w = self.shape
        for (y, x), ghost in self.ghost_cells.items():
            ghost.history[epoch] = int(padded[y + 1, x + 1])
            for gid in ghost.queued_requests.pop(epoch, []):
                self._mailbox.append(("state_reply", gid, (y, x), ghost.history[epoch]))
        self._drain()


class ActorTileEngine:
    """``engine="actor"`` adapter for :class:`BackendWorker`: steps a tile by
    per-cell actor message passing instead of a dense kernel.  Stateful per
    tile; a redeploy constructs a fresh engine (supervision restart)."""

    def __init__(self, rule) -> None:
        self.rule = resolve_rule(rule)
        self._board: Optional[_TileActorBoard] = None
        self._epoch = 0  # internal epoch counter (0 = deploy epoch)

    def step(self, padded: np.ndarray) -> np.ndarray:
        interior = padded[1:-1, 1:-1]
        if self._board is None:
            self._board = _TileActorBoard(interior, self.rule)
        self._board.feed_halo(self._epoch, padded)
        self._epoch += 1
        self._board.advance_to(self._epoch)
        assert self._board.min_epoch() == self._epoch
        # Bounded history: crash recovery goes through redeploy (a fresh
        # engine), never through in-place replay, so only the previous epoch
        # (the set_state guard) is ever read again.
        self._board.prune_histories_below(self._epoch - 1)
        for ghost in self._board.ghost_cells.values():
            ghost.history = {
                e: s for e, s in ghost.history.items() if e >= self._epoch - 1
            }
        return self._board.board_at_current()
