"""Product self-test: the reference's manual verification procedure as a
command.

The reference's de-facto test plan is manual — start JVMs, tail ``info.log``,
ctrl+c a backend, eyeball that the board survives
(``/root/reference/README.md:3-12``).  ``python -m akka_game_of_life_tpu
selftest`` automates that contract against whatever hardware the process
sees: every check drives the PUBLIC Simulation surface (the same code path
as ``run``), reports one JSON line per check, and exits non-zero on any
failure.  Run it on a new machine/TPU before trusting a long job.

Checks:
  gun-phase        Gosper gun period-30 phase on the selected kernel
  oracle           selected kernel ≡ dense oracle on a random board
  checkpoint       save → crash → restore → replay ≡ uninterrupted run
  chaos            injected crash mid-run leaves the trajectory bit-identical
  sharded          (multi-device only) meshed stepping ≡ single-device
  families         wireworld clock phase + LtL-R1 ≡ classic (cross-unit)
  obs-defer        deferred observation ≡ synchronous on this hardware
"""

from __future__ import annotations

import io
import json
import tempfile
import time
from typing import Callable, List, Optional

import numpy as np


def _sim(tmp=None, observer_out=None, **kw):
    from akka_game_of_life_tpu.runtime.config import SimulationConfig
    from akka_game_of_life_tpu.runtime.render import BoardObserver
    from akka_game_of_life_tpu.runtime.simulation import Simulation

    # 1024 rows: per-shard heights keep an 8-multiple block-row divisor on
    # any 1-8 device topology, so kernel=auto can resolve to pallas on a
    # meshed TPU (96-row boards would shard to 12 rows on a v5e-8 and
    # silently demote every check to bitpack).
    base = dict(height=1024, width=512, rule="conway", seed=9, steps_per_call=6)
    if tmp is not None:
        base.update(checkpoint_dir=str(tmp), checkpoint_every=12)
    base.update(kw)
    return Simulation(
        SimulationConfig(**base),
        observer=BoardObserver(out=observer_out or io.StringIO()),
    )


def _dense(board: np.ndarray, steps: int) -> np.ndarray:
    import jax.numpy as jnp

    from akka_game_of_life_tpu.models import get_model

    return np.asarray(get_model("conway").run(steps)(jnp.asarray(board)))


def _check_gun_phase(kernel: str) -> str:
    sim = _sim(
        pattern="gosper-glider-gun",
        pattern_offset=(4, 4),
        kernel=kernel,
        steps_per_call=15,
    )
    # Checks close their sims so stores/buffers never outlive the check
    # (and, for tmp-dir checks, never race directory removal).
    g0 = sim.board_window(4, 13, 4, 40)
    pop0 = int(sim.board_host().sum())
    sim.advance(15)  # mid-period: the window MUST differ (frozen-stepper guard)
    assert not np.array_equal(sim.board_window(4, 13, 4, 40), g0), (
        "board did not evolve (stepper frozen?)"
    )
    sim.advance(45)  # epoch 60 = two periods
    assert np.array_equal(sim.board_window(4, 13, 4, 40), g0), (
        "gun out of phase after two periods"
    )
    assert int(sim.board_host().sum()) == pop0 + 10, (
        "gun did not emit two gliders over two periods"
    )
    sim.close()
    return sim.kernel


def _check_oracle(kernel: str) -> str:
    sim = _sim(kernel=kernel)
    start = sim.board_host()
    sim.advance(36)
    want = _dense(start, 36)
    assert np.array_equal(sim.board_host(), want), "kernel diverged from dense oracle"
    sim.close()
    return sim.kernel


def _check_checkpoint(kernel: str) -> str:
    with tempfile.TemporaryDirectory() as tmp:
        sim = _sim(tmp=tmp, kernel=kernel)
        start = sim.board_host()
        sim.advance(24)
        sim.close()  # durable
        resumed = _sim(tmp=tmp, kernel=kernel)
        assert resumed.epoch == 24, f"resume found epoch {resumed.epoch}, want 24"
        resumed.advance(12)
        assert np.array_equal(resumed.board_host(), _dense(start, 36)), (
            "post-resume trajectory diverged"
        )
        resumed.close()
        return resumed.kernel


def _check_chaos(kernel: str) -> str:
    from akka_game_of_life_tpu.runtime.config import FaultInjectionConfig

    with tempfile.TemporaryDirectory() as tmp:
        chaotic = _sim(
            tmp=tmp,
            kernel=kernel,
            fault_injection=FaultInjectionConfig(
                enabled=True, first_after_epochs=12, every_epochs=24, max_crashes=1
            ),
        )
        start = chaotic.board_host()
        chaotic.advance(36)
        assert chaotic.crash_log, "injector never fired"
        assert np.array_equal(chaotic.board_host(), _dense(start, 36)), (
            "crash+replay diverged from uninterrupted trajectory"
        )
        chaotic.close()
        return chaotic.kernel


def _check_sharded(kernel: str) -> str:
    import jax

    if len(jax.devices()) < 2:
        raise _Skip(f"single device ({jax.devices()[0].platform})")
    sim = _sim(kernel=kernel)  # auto mesh over all devices
    if sim.mesh is None:
        raise _Skip("kernel resolved to an unmeshed path")
    start = sim.board_host()
    sim.advance(36)
    assert np.array_equal(sim.board_host(), _dense(start, 36)), (
        "meshed trajectory diverged from dense oracle"
    )
    sim.close()
    return sim.kernel


def _check_families(kernel: str) -> str:
    """The non-Conway rule families: the wireworld clock must hold its
    period-10 phase on whatever kernel this machine resolves (the packed
    2-bit-plane path on 32-aligned widths), and a radius-1 LtL Conway must
    be bit-identical to the classic kernel (the shift-add-vs-SWAR
    cross-formulation anchor)."""
    import jax.numpy as jnp

    from akka_game_of_life_tpu.models import get_model
    from akka_game_of_life_tpu.ops.rules import Rule
    from akka_game_of_life_tpu.utils.patterns import pattern_board  # noqa: F401

    ww = _sim(rule="wireworld", pattern="wireworld-clock", pattern_offset=(8, 8),
              height=64, width=64, steps_per_call=5)
    ww_kernel = ww.kernel
    start = ww.board_window(8, 12, 8, 13)
    assert start.sum() > 0
    ww.advance(10)
    assert np.array_equal(ww.board_window(8, 12, 8, 13), start), (
        "wireworld clock lost phase"
    )
    ww.close()

    board = pattern_board("acorn", (128, 128), (60, 60))
    classic = _dense(board, 32)
    as_ltl = Rule(frozenset({3}), frozenset({2, 3}), kind="ltl")
    via_ltl = np.asarray(get_model(as_ltl).run(32)(jnp.asarray(board)))
    assert np.array_equal(via_ltl, classic), "ltl path diverged from classic"
    return f"wireworld={ww_kernel}, ltl=dense"


def _check_obs_defer(kernel: str) -> str:
    """Deferred observation ≡ synchronous: same cadence epochs, the same
    populations, the same probe-window cells, the same final board — run
    on whatever kernel this machine resolves, so the mode's on-hardware
    behavior (fetch-one-chunk-later over the real device link) is part of
    the product's self-verification."""
    outs = {}
    for defer in (False, True):
        out = io.StringIO()
        sim = _sim(
            observer_out=out,
            pattern="gosper-glider-gun",
            pattern_offset=(4, 4),
            kernel=kernel,
            metrics_every=12,
            render_every=30,
            probe_window=(4, 13, 4, 40),
            obs_defer=defer,
        )
        sim.advance(60)
        sim.close()
        history = [(m.epoch, m.population) for m in sim.observer.history]
        windows = [
            l for l in out.getvalue().splitlines() if "window" in l
        ]
        outs[defer] = (history, windows, sim.board_host(), sim.kernel)
    assert outs[False][0] == outs[True][0], "metrics history diverged"
    assert outs[False][0], "no cadence points observed"
    assert outs[False][1], "no probe windows observed"
    assert outs[False][1] == outs[True][1], "probe windows diverged"
    assert np.array_equal(outs[False][2], outs[True][2]), "final board diverged"
    return outs[True][3]


class _Skip(Exception):
    pass


CHECKS: List[tuple] = [
    ("gun-phase", _check_gun_phase),
    ("oracle", _check_oracle),
    ("checkpoint", _check_checkpoint),
    ("chaos", _check_chaos),
    ("sharded", _check_sharded),
    ("families", _check_families),
    ("obs-defer", _check_obs_defer),
]


def run_selftest(
    kernel: str = "auto", out: Optional[Callable[[str], None]] = None
) -> int:
    """Run every check; print one JSON line each; return the failure count."""
    import jax

    emit = out or (lambda s: print(s, flush=True))
    failures = 0
    for name, check in CHECKS:
        t0 = time.perf_counter()
        line = {"check": name, "kernel": kernel, "backend": jax.default_backend()}
        try:
            # Checks return the kernel the Simulation actually resolved to
            # (and possibly demoted to) — the fact a green selftest exists
            # to establish on new hardware.
            line["resolved"] = check(kernel)
            line["status"] = "pass"
        except _Skip as s:
            line["status"] = "skip"
            line["reason"] = str(s)
        except Exception as e:  # noqa: BLE001 — a selftest reports, never raises
            line["status"] = "fail"
            line["error"] = f"{type(e).__name__}: {e}"
            failures += 1
        line["seconds"] = round(time.perf_counter() - t0, 3)
        emit(json.dumps(line))
    return failures
