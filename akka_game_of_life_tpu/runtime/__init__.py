from akka_game_of_life_tpu.runtime.config import (  # noqa: F401
    FaultInjectionConfig,
    SimulationConfig,
    load_config,
    parse_duration,
)
from akka_game_of_life_tpu.runtime.render import BoardObserver, render_ascii  # noqa: F401
from akka_game_of_life_tpu.runtime.checkpoint import Checkpoint, CheckpointStore  # noqa: F401
