"""Signal plumbing shared by the graceful-shutdown paths.

The CLI maps SIGTERM onto KeyboardInterrupt so ^C and orchestrator stops
share one shutdown path (``cli._sigterm_as_interrupt``); the pieces here
protect the *cleanup* that path runs.  The reference has no analog — its
JVMs die where they stand (``README.md:12`` tells the operator to ctrl+c a
backend and watch the survivors cope).
"""

from __future__ import annotations

import contextlib
import signal
import threading


@contextlib.contextmanager
def mask_interrupts():
    """Ignore SIGINT/SIGTERM for the duration of a graceful drain.

    Once shutdown cleanup has started (SHUTDOWN fan-out, checkpoint-queue
    drain, store close), a second ^C/SIGTERM would abort it half-way while
    still exiting with the "clean" status code — worse than either outcome
    alone.  Cleanup is bounded work, so the signals are ignored rather than
    deferred; an operator who truly needs an immediate stop has SIGKILL.
    No-op off the main thread, and C-installed handlers (getsignal() →
    None — unrestorable through the signal module) are left untouched.
    """
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    masked = []
    try:
        for sig in (signal.SIGINT, signal.SIGTERM):
            if signal.getsignal(sig) is None:
                continue
            masked.append((sig, signal.signal(sig, signal.SIG_IGN)))
    except BaseException:
        for sig, old in masked:
            signal.signal(sig, old)
        raise
    try:
        yield
    finally:
        for sig, old in masked:
            signal.signal(sig, old)
