"""Signal plumbing shared by the graceful-shutdown paths.

The CLI maps SIGTERM onto KeyboardInterrupt so ^C and orchestrator stops
share one shutdown path (``cli._sigterm_as_interrupt``); the pieces here
protect the *cleanup* that path runs.  The reference has no analog — its
JVMs die where they stand (``README.md:12`` tells the operator to ctrl+c a
backend and watch the survivors cope).
"""

from __future__ import annotations

import contextlib
import signal
import threading


@contextlib.contextmanager
def flight_dump_on_signals(recorder, *, reason: str = "sigterm", signals=None):
    """Dump the flight recorder when SIGTERM lands, then run the previous
    handler.

    Installed around a role's serve loop (inside the CLI's
    SIGTERM→KeyboardInterrupt mapping), so an orchestrator stop leaves the
    same post-mortem artifact an injected crash does — the last N spans and
    events at the moment the stop arrived — before the graceful-shutdown
    path runs.  The dump itself is failure-contained (it never raises), so
    it cannot break the shutdown it decorates.

    Chains: our handler dumps, then delegates to whatever handler was
    installed before (the CLI's KeyboardInterrupt-raiser in practice); a
    SIG_DFL/SIG_IGN predecessor is restored and left to fire naturally.
    Main thread only; C-installed handlers (getsignal() → None) are left
    untouched, same policy as :func:`mask_interrupts`.
    """
    if signals is None:
        signals = (signal.SIGTERM,)
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    installed = []

    def _make(sig, prev):
        def handler(signum, frame):
            recorder.dump(reason)
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                # Re-deliver with the default disposition (usually: die).
                signal.signal(signum, signal.SIG_DFL)
                import os

                os.kill(os.getpid(), signum)

        return handler

    try:
        for sig in signals:
            prev = signal.getsignal(sig)
            if prev is None:
                continue  # C-installed: unrestorable through this module
            installed.append((sig, signal.signal(sig, _make(sig, prev))))
    except BaseException as e:
        for sig, old in installed:
            signal.signal(sig, old)
        if isinstance(e, ValueError):  # no signal support in this context
            yield
            return
        raise
    try:
        yield
    finally:
        for sig, old in installed:
            signal.signal(sig, old)


@contextlib.contextmanager
def stop_after(timeout_s: float, stop_fn):
    """Bound a graceful wait: run ``stop_fn`` if the block outlives
    ``timeout_s``.

    Used by the backend's SIGTERM drain: the worker keeps serving the
    migration protocol until the frontend releases it, but an unreachable
    or wedged frontend must not turn an orchestrator stop into a hang —
    past the deadline the watchdog forces the worker's own stop() and the
    caller falls back to the abrupt-leave path.  The timer thread is a
    daemon and is cancelled on every exit path, so a prompt release costs
    nothing."""
    timer = threading.Timer(timeout_s, stop_fn)
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()


@contextlib.contextmanager
def mask_interrupts():
    """Ignore SIGINT/SIGTERM for the duration of a graceful drain.

    Once shutdown cleanup has started (SHUTDOWN fan-out, checkpoint-queue
    drain, store close), a second ^C/SIGTERM would abort it half-way while
    still exiting with the "clean" status code — worse than either outcome
    alone.  Cleanup is bounded work, so the signals are ignored rather than
    deferred; an operator who truly needs an immediate stop has SIGKILL.
    No-op off the main thread, and C-installed handlers (getsignal() →
    None — unrestorable through the signal module) are left untouched.
    """
    if threading.current_thread() is not threading.main_thread():
        yield
        return
    masked = []
    try:
        for sig in (signal.SIGINT, signal.SIGTERM):
            if signal.getsignal(sig) is None:
                continue
            masked.append((sig, signal.signal(sig, signal.SIG_IGN)))
    except BaseException:
        for sig, old in masked:
            signal.signal(sig, old)
        raise
    try:
        yield
    finally:
        for sig, old in masked:
            signal.signal(sig, old)
