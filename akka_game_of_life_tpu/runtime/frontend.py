"""The frontend coordinator — the ``BoardCreator`` + ``RunFrontend`` role.

One process drives the cluster, exactly as in the reference
(``Run.scala:15-54``, ``BoardCreator.scala``): it is the seed node workers
join, the membership tracker, the placement authority, the epoch driver, the
fault injector, the render sink, and the recovery orchestrator.  What changed
is the *unit*: the reference deploys one actor per cell and re-wires 8
``ActorRef``s per crash; this frontend deploys one HBM-resident tile per
worker and re-deploys tiles from durable checkpoints with deterministic
replay (SURVEY.md §7.6-7.7).

Failure model implemented here (the reference's three layers, §5):
- *detection*: connection EOF (DeathWatch) + stale heartbeat (auto-down);
- *recovery*: tile redeployment onto survivors, restored from the last
  checkpoint (or the deterministic initial board) and replayed forward by
  pulling epoch-tagged boundary rings (``onCellTermination``,
  ``BoardCreator.scala:138-154``, without the epoch-0 replay cost);
- *injection*: the scheduled ``crashIfIMay`` killer with budget
  (``BoardCreator.scala:97-102``) in two flavors: node kill and tile kill.
"""

from __future__ import annotations

import dataclasses
import queue
import socket
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs import (
    EventLog,
    MetricsDumper,
    MetricsServer,
    get_registry,
)
from akka_game_of_life_tpu.obs.programs import get_programs
from akka_game_of_life_tpu.obs.programs import http_routes as program_routes
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.ops.rules import resolve_rule
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.checkpoint import make_store
from akka_game_of_life_tpu.runtime.chaos import CrashInjector
from akka_game_of_life_tpu.runtime.config import SimulationConfig
from akka_game_of_life_tpu.runtime.membership import Member, Membership
from akka_game_of_life_tpu.runtime.netchaos import (
    ChaosChannel,
    NetworkChaos,
    wrap_channel,
)
from akka_game_of_life_tpu.runtime.rebalance import Migration, Rebalancer
from akka_game_of_life_tpu.runtime.render import BoardObserver
from akka_game_of_life_tpu.runtime.simulation import initial_board
from akka_game_of_life_tpu.runtime.tiles import TileId, TileLayout, layout_for_workers
from akka_game_of_life_tpu.runtime.wire import (
    MAX_FRAME,
    Channel,
    attach_trace,
    pack_tile,
    unpack_tile,
)

_MAINT_INTERVAL_S = 0.05

# Cadence of the frontend's --metrics-file rewrites.  The standalone runner
# dumps at its epoch-indexed metrics cadence; the coordinator has no
# per-epoch loop of its own, so it refreshes the exposition on wall time —
# a file collector scrapes a live view mid-run, not only the exit snapshot.
_METRICS_DUMP_INTERVAL_S = 5.0

# Cadence of *in-memory* checkpoints when no durable cadence is configured.
# The frontend needs a periodic per-tile snapshot anyway: it is both the
# recovery source for redeploys and the floor below which boundary rings are
# pruned — without it ring history grows forever (the reference's
# unbounded-History bug, SURVEY.md §2 bug 5, at tile granularity).
_MEMORY_CKPT_EVERY = 32

# Assemble a full final board in memory only below this cell count; above it
# (65536²-class boards) the durable per-tile checkpoint IS the final output.
_ASSEMBLE_LIMIT = 1 << 28


class MalformedMessage(Exception):
    """A structurally invalid message from a registered worker — grounds to
    drop the connection (tiles redeploy), never to crash a serve thread."""


# Required fields per message type, checked BEFORE dispatch so a missing
# field can never surface as a KeyError inside cluster bookkeeping.
_MSG_REQUIRED = {
    P.PROGRESS: ("tile", "epoch"),
    P.TILE_STATE: ("tile", "epoch"),
    P.REDEPLOY_REQUEST: ("tile",),
    P.GATHER_FAILED: ("tile", "epoch"),
    P.MIGRATE_STATE: ("tile", "epoch", "state", "digest", "seq"),
    P.DRAIN_REQUEST: (),
    P.SERVE_RESULT: ("results",),
    P.SHARD_STATE: ("shard", "seq"),
    P.SHARD_HOME: ("sessions",),
}
# TILE_STATE carries per-reason payloads; each declared reason needs its key.
_REASON_PAYLOAD = {
    "final": ("state",),
    "checkpoint": ("state",),
    "render": ("scaled_origin", "sample"),
    "metrics": ("population",),
}


def _validate_msg(msg) -> None:
    if not isinstance(msg, dict):
        raise MalformedMessage(f"non-dict payload ({type(msg).__name__})")
    kind = msg.get("type")
    if not isinstance(kind, str):
        raise MalformedMessage(f"message type {kind!r} is not a string")
    required = _MSG_REQUIRED.get(kind, ())
    for field in required:
        if field not in msg:
            raise MalformedMessage(f"{kind} message missing {field!r}")
    if "tile" in required:
        tile = msg["tile"]
        if not (
            isinstance(tile, (list, tuple))
            and len(tile) == 2
            and all(isinstance(v, int) for v in tile)
        ):
            raise MalformedMessage(
                f"{kind} tile {tile!r} is not an integer (row, col) pair"
            )
    if "epoch" in required and not isinstance(msg["epoch"], int):
        raise MalformedMessage(f"{kind} epoch {msg['epoch']!r} is not an int")
    if "seq" in required and not isinstance(msg["seq"], int):
        raise MalformedMessage(f"{kind} seq {msg.get('seq')!r} is not an int")
    if "shard" in required and not isinstance(msg["shard"], int):
        raise MalformedMessage(
            f"{kind} shard {msg.get('shard')!r} is not an int"
        )
    if "results" in required and not isinstance(msg["results"], list):
        raise MalformedMessage(f"{kind} results is not a list")
    if "state" in required and not isinstance(msg["state"], dict):
        raise MalformedMessage(f"{kind} state is not a tile payload dict")
    if kind == P.PROGRESS:
        for field in ("q", "skipped"):
            if field in msg and not isinstance(msg[field], int):
                raise MalformedMessage(
                    f"progress {field} {msg[field]!r} is not an int"
                )
    if kind in (P.PROGRESS, P.MIGRATE_STATE) and "digest" in msg:
        d = msg["digest"]
        if not (
            isinstance(d, (list, tuple))
            and len(d) == 2
            and all(isinstance(v, int) for v in d)
        ):
            raise MalformedMessage(
                f"{kind} digest {d!r} is not an integer (lo, hi) pair"
            )
    if kind == P.TILE_STATE:
        reasons = msg.get("reasons", [])
        if not isinstance(reasons, (list, tuple)) or not all(
            isinstance(r, str) for r in reasons
        ):
            raise MalformedMessage(
                f"tile_state reasons {reasons!r} not a list of strings"
            )
        for reason in reasons:
            for field in _REASON_PAYLOAD.get(reason, ()):
                if field not in msg:
                    raise MalformedMessage(
                        f"tile_state[{reason}] missing {field!r}"
                    )
        if "window" in msg and "window_origin" not in msg:
            raise MalformedMessage("tile_state window missing 'window_origin'")


class Frontend:
    """Coordinator state machine.  Thread layout: one acceptor, one reader
    thread per worker connection, one maintenance thread (ticks, heartbeat
    eviction, fault injection)."""

    # Lock discipline (tools/graftlint, pass GL-LOCK01): the coordinator
    # RLock orders every piece of cluster bookkeeping the reader threads
    # and the maintenance thread both touch.  Helpers documented "caller
    # holds the lock" carry the *_locked suffix.  Set-once references
    # (config, rule, store, observer, membership — internally consistent
    # or single-writer) are deliberately undeclared.
    _GRAFTLINT_GUARDED = {
        "tile_owner": "_lock",
        "tile_epochs": "_lock",
        "target_epoch": "_lock",
        "paused": "_lock",
        "layout": "_lock",
        "quiescent": "_lock",
        "_last_ring_time": "_lock",
        "_redeploy_times": "_lock",
        "_last_ckpt": "_lock",
        "_ckpt_pending": "_lock",
        "_final_tiles": "_lock",
        "final_board": "_lock",
        "_digest_partial": "_lock",
        "_digest_floor": "_lock",
        "epoch_digests": "_lock",
        "final_digest": "_lock",
        "error": "_lock",
        "_next_tick": "_lock",
        "_drain_spans": "_lock",
        "_degraded_span": "_lock",
        "degraded": "_lock",
    }

    def __init__(
        self,
        config: SimulationConfig,
        *,
        min_backends: int = 1,
        observer: Optional[BoardObserver] = None,
        registry=None,
        tracer=None,
    ) -> None:
        if config.max_epochs is None and not config.serve_cluster:
            # A serve-only cluster (serve_cluster with no simulation) has
            # no epoch target: the frontend is membership + serve plane.
            raise ValueError("frontend requires max_epochs")
        if config.max_epochs is None:
            config = dataclasses.replace(config, max_epochs=0)
        self.config = config
        self.rule = resolve_rule(config.rule)
        # Coordinator observability: membership churn and recovery actions
        # as counters/gauges, lifecycle as JSONL events, both exposed live
        # at /metrics + /healthz + /trace when metrics_port is set (started
        # in :meth:`start`).  The tracer's epoch span context rides inside
        # TICK/DEPLOY/CRASH envelopes so worker spans join the epoch trace.
        self.metrics = registry if registry is not None else get_registry()
        if tracer is None:
            tracer = get_tracer()
            # Role-label the process tracer so nodeless spans (checkpoint
            # IO on the io thread) attribute to this role, not "proc".
            tracer.node = "frontend"
        self.tracer = tracer
        self.tracer.flight.configure(
            directory=config.flight_dir, node="frontend"
        )
        self.events = EventLog(
            config.log_events, node="frontend", recorder=self.tracer.flight
        )
        # Compile & cost observatory: the frontend is the cluster merge
        # point — its process registry gets the role identity and alert
        # sinks (storms fire into the same event log as promotions), and
        # every worker COST frame folds in through merge_remote.  The
        # profiler powers POST /profile; the rate limiter lives HERE (one
        # cluster knob), workers just obey the fan-out.
        self.programs = get_programs().configure(
            node="frontend",
            events=self.events,
            flight=self.tracer.flight,
            metrics=self.metrics,
            enabled=config.obs_programs,
        )
        from akka_game_of_life_tpu.runtime.profiling import ProfilerCapture

        self._profiler = ProfilerCapture(
            config.flight_dir or "artifacts",
            node="frontend",
            max_seconds=config.obs_profile_max_s,
            min_interval_s=config.obs_profile_min_interval_s,
        )
        # cluster.run is the whole simulation; epoch is one epoch-target
        # announcement (the whole run in free-running mode, one tick in
        # paced mode) — the span every backend step links back to.
        self._run_span = None
        self._epoch_span = None
        self._metrics_dumper = (
            MetricsDumper(
                self.metrics,
                config.metrics_file,
                interval_s=_METRICS_DUMP_INTERVAL_S,
            )
            if config.metrics_file
            else None
        )
        self._m_alive = self.metrics.gauge("gol_members_alive")
        self._m_joined = self.metrics.counter("gol_members_joined_total")
        self._m_lost = self.metrics.counter("gol_members_lost_total")
        self._m_redeploys = self.metrics.counter("gol_redeploys_total")
        self._m_degraded = self.metrics.gauge("gol_degraded_mode")
        self._m_degraded_entries = self.metrics.counter(
            "gol_degraded_entries_total"
        )
        self._m_tiles_skipped = self.metrics.counter(
            "gol_tiles_skipped_total"
        )
        self._m_tiles_quiescent = self.metrics.gauge("gol_tiles_quiescent")
        self._m_digest_checks = self.metrics.counter("gol_digest_checks_total")
        self._m_digest_mismatches = self.metrics.counter(
            "gol_digest_mismatches_total"
        )
        self._m_digest_seconds = self.metrics.histogram("gol_digest_seconds")
        # Elastic plane observability: per-member control-plane staleness
        # (the operator's early-warning gauge before auto-down fires),
        # migration outcomes, and drain progress.
        self._m_hb_age = self.metrics.gauge(
            "gol_member_heartbeat_age_seconds",
            "Seconds since each member's last control-plane traffic",
            ("member",),
        )
        self._m_draining = self.metrics.gauge("gol_members_draining")
        self._m_migrations = self.metrics.counter("gol_migrations_total")
        self._m_migration_aborts = self.metrics.counter(
            "gol_migration_aborts_total"
        )
        self._m_migration_seconds = self.metrics.histogram(
            "gol_migration_seconds"
        )
        self._m_drains = self.metrics.counter("gol_drains_total")
        self._metrics_server: Optional[MetricsServer] = None
        self._serve_slo = None  # SloTracker when serve_cluster is on
        # Wire-fault policy (config/CLI --chaos-net-*): one seeded instance
        # per process; the in-process harness hands this same instance to
        # its workers so partition sides are consistent cluster-wide.
        self.netchaos = (
            NetworkChaos(
                config.net_chaos, registry=self.metrics, tracer=self.tracer
            )
            if config.net_chaos.enabled
            else None
        )
        if self.netchaos is not None:
            self.netchaos.register_node("frontend")
        # Degraded mode: a partition has stranded a quorum of tiles past
        # stuck_timeout_s — the run checkpoints what it has and WAITS for
        # the heal instead of auto-downing live members / thrashing the
        # restart budget on tiles nobody can actually step.
        self.degraded = False
        self._degraded_span = None
        if self.rule.radius != 1:
            raise ValueError(
                "the TCP cluster exchanges radius-1 boundary rings; "
                "radius-R ltl rules run standalone (single-chip or a "
                "jax.distributed mesh, where the halo is radius-aware)"
            )
        self.min_backends = min_backends
        self.observer = observer or BoardObserver(
            render_every=config.render_every,
            render_max_cells=config.render_max_cells,
            metrics_every=config.metrics_every,
            log_file=config.log_file,
            registry=self.metrics,
        )
        # Fault schedules: the wall-clock killer (BoardCreator.scala:97-102)
        # polls from the maintenance loop; the epoch-indexed schedule is
        # anchored to cluster progress instead — it fires from the PROGRESS
        # handler once the slowest tile reaches first_after_epochs (then
        # every every_epochs).  Epoch anchoring is what makes chaos drills
        # deterministic: a fast run cannot outrace the injector, because the
        # schedule is indexed by the very epochs the run must produce.
        self.membership = Membership(config.failure_timeout_s)
        # The elastic plane (docs/OPERATIONS.md "Elastic rebalancing"):
        # live tile migration, mid-run scale-out, graceful drain.  Always
        # constructed — drains use it on every cluster; rebalance_enabled
        # only gates the automatic load-driven planning.  Mutated strictly
        # under self._lock.
        self.rebalancer = Rebalancer(config)
        self._drain_spans: Dict[str, object] = {}
        # Cluster-sharded serving (docs/OPERATIONS.md "Serving plane"):
        # when serve_cluster is on, this frontend is ALSO the tenant-facing
        # session router — sessions hash-shard across the same workers that
        # host tiles, /boards mounts on the obs endpoint, and the plane's
        # own Rebalancer instance migrates session shards (load + drain).
        self.serve_plane = None
        if config.serve_cluster:
            from akka_game_of_life_tpu.serve.cluster import ClusterServePlane

            self.serve_plane = ClusterServePlane(
                config,
                self.membership,
                self._safe_send,
                registry=self.metrics,
                tracer=self.tracer,
                events=self.events,
            )
        if config.checkpoint_dir and config.checkpoint_format != "npz":
            # The cluster frontend streams per-tile saves (save_tile /
            # finalize_epoch), which only the npz store implements; orbax is
            # the standalone runner's device-native store.
            raise ValueError(
                "cluster frontend requires checkpoint_format='npz' "
                f"(got {config.checkpoint_format!r})"
            )
        self.store = (
            make_store(
                config.checkpoint_dir,
                config.checkpoint_format,
                registry=self.metrics,
                tracer=self.tracer,
            )
            if config.checkpoint_dir
            else None
        )
        # Created in start_simulation so the error.delay schedule counts from
        # simulation start, not from process start (workers may take a long
        # time to join during wait-for-backends).
        self.injector: Optional[CrashInjector] = None

        self.layout: Optional[TileLayout] = None
        self.tile_owner: Dict[TileId, str] = {}
        self.tile_epochs: Dict[TileId, int] = {}
        self.target_epoch = 0
        self.start_epoch = 0
        self.paused = False
        self.crash_events: List[dict] = []
        # Supervision budget (OneForOneStrategy ≤10 restarts/min,
        # BoardCreator.scala:42-45): recent redeploy timestamps per tile.
        self._redeploy_times: Dict[TileId, Deque[float]] = {}
        # Per-tile progress clock (last RING received) — the evidence a
        # GATHER_FAILED escalation is judged against.
        self._last_ring_time: Dict[TileId, float] = {}
        # Quiescence tier (sparse_cluster): tiles currently reporting
        # themselves quiescent (tile -> period).  Exempted — while their
        # pings stay fresh — from the stuck-neighbor redeploy and the
        # degraded-mode stranded count (silence at cadence granularity is
        # the feature, not a fault), and surfaced in /healthz.  Cleared on
        # any ownership change; the new owner re-detects from scratch.
        self.quiescent: Dict[TileId, int] = {}
        # Checkpoint cadence workers report at; falls back to an in-memory
        # cadence so ring pruning and recovery work without a durable store.
        self._ckpt_cadence = config.checkpoint_every or _MEMORY_CKPT_EVERY
        if self._ckpt_cadence % config.exchange_width:
            # Tiles only visit exchange_width-aligned epochs (config
            # validates the explicit cadences; the in-memory fallback must
            # hold the same invariant or recovery epochs would never land).
            self._ckpt_cadence = (
                self._ckpt_cadence
                + config.exchange_width
                - self._ckpt_cadence % config.exchange_width
            )

        # Recovery source: (epoch, {tile: bit-packed payload}).  Kept packed
        # (8 cells/byte) so a 65536² board's recovery state is ~512 MiB, and
        # the full board is never assembled on this process (VERDICT weak #5).
        self._last_ckpt: Optional[Tuple[int, Dict[TileId, dict]]] = None
        self._ckpt_pending: Dict[int, Dict[TileId, dict]] = {}
        self._final_tiles: Dict[TileId, dict] = {}
        self.final_board: Optional[np.ndarray] = None
        # Digest plane (obs_digest): per-tile fingerprint lanes arrive on
        # PROGRESS pings at digest-due epochs and merge here in O(tiles)
        # bytes — the cluster's whole-board state certificate without any
        # board assembly.  epoch_digests holds the last few merged 64-bit
        # values (finalized checkpoints copy theirs into COMPLETE.json);
        # final_digest is the max_epochs certificate bench/tests compare.
        self._digest_partial: Dict[int, Dict[TileId, Tuple[int, int]]] = {}
        self._digest_floor: Optional[int] = None
        self.epoch_digests: Dict[int, int] = {}
        self.final_digest: Optional[int] = None
        self.error: Optional[str] = None

        self._lock = threading.RLock()
        self._started = threading.Event()
        self.done = threading.Event()
        self._stop = threading.Event()
        self._next_tick: Optional[float] = None
        # Checkpoint IO rides its own thread: a reader thread that blocks on
        # disk stops draining its worker's socket, which can starve that
        # worker's heartbeats behind bulk sends and auto-down a live member.
        self._io_queue: "queue.Queue[Optional[Tuple[str, tuple]]]" = queue.Queue()

        self._listener = socket.create_server(
            (config.host, config.port), reuse_port=False
        )
        self.port = self._listener.getsockname()[1]
        # Frontend federation (docs/OPERATIONS.md "Frontend scale-out &
        # HA"): when --frontend-seeds names peer frontends, this frontend
        # gossips membership + slice ownership with them, forwards
        # foreign-slice serve ops, and replicates its control state to a
        # rendezvous standby.  Constructed after the listener so the
        # advertised identity carries the real bound port.
        self.federation = None
        if self.serve_plane is not None and config.frontend_seeds:
            from akka_game_of_life_tpu.serve.federation import FederationPlane

            adv = config.frontend_advertise or (
                f"{config.host}:{self.port}"
            )
            host, _, port_s = adv.rpartition(":")
            if host in ("0.0.0.0", ""):
                host = "127.0.0.1"
            self.federation = FederationPlane(
                config, self.serve_plane,
                name=f"{host}:{int(port_s)}",
                cluster_addr=(host, int(port_s)),
                events=self.events,
            )
            self.federation.on_peers_changed(self._push_fed_peers)
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.config.metrics_port or self.serve_plane is not None:
            # Observatory surface on every frontend: cluster-merged
            # /programs + /cost, and POST /profile fanning a capture to
            # the workers.
            routes = dict(
                program_routes(
                    registry=self.programs, profile=self._cluster_profile
                )
            )
            if self.serve_plane is not None:
                from akka_game_of_life_tpu.obs import slo as slo_mod
                from akka_game_of_life_tpu.serve.api import board_routes

                # The tenant surface rides the obs endpoint, exactly like
                # the single-process serve role (ephemeral port when no
                # metrics_port was configured — printed by the role body).
                # The SLO tracker gets the frontend's event log so burn
                # alerts land in the same stream as promotions.
                self._serve_slo = slo_mod.SloTracker(
                    self.config, registry=self.metrics, tracer=self.tracer,
                    events=self.events, node="frontend",
                )
                routes.update(
                    board_routes(
                        # With federation on, /boards mounts the federated
                        # router: same surface, one extra routing level
                        # (slice owner) above the plane's shard table.
                        self.federation.router
                        if self.federation is not None
                        else self.serve_plane,
                        tracer=self.tracer,
                        slo=self._serve_slo,
                    )
                )
            self._metrics_server = MetricsServer(
                self.metrics,
                port=self.config.metrics_port,
                health=self._health,
                tracer=self.tracer,
                routes=routes,
            )
        if self.federation is not None:
            if self._metrics_server is not None:
                # Peers learn this HTTP endpoint via gossip — it is where
                # their 307 redirects for this frontend's boards point.
                self.federation.set_http_port(self._metrics_server.port)
            self.federation.start()
        for fn in (self._accept_loop, self._maintenance_loop, self._io_loop):
            t = threading.Thread(target=fn, daemon=True, name=fn.__name__)
            t.start()
            self._threads.append(t)

    def _health(self) -> dict:
        """The /healthz document: ok until the run has errored — plus the
        live facts an operator checks first (members, epoch floor, done).
        Per-member heartbeat age surfaces control-plane staleness BEFORE
        auto-down fires (it also lives in the
        gol_member_heartbeat_age_seconds gauge)."""
        now = time.monotonic()
        with self._lock:
            alive = self.membership.alive_members()
            doc = {
                "ok": self.error is None,
                "error": self.error,
                "members_alive": len(alive),
                "heartbeat_age_s": {
                    m.name: round(max(0.0, now - m.last_seen), 3)
                    for m in alive
                },
                "draining": sorted(m.name for m in alive if m.draining),
                "migrations_inflight": len(self.rebalancer.inflight),
                "tiles_quiescent": len(self.quiescent),
                "epoch_floor": min(self.tile_epochs.values(), default=0),
                "target_epoch": self.target_epoch,
                "done": self.done.is_set(),
                "paused": self.paused,
                "degraded": self.degraded,
            }
        if self.serve_plane is not None:
            # Outside the frontend lock (frontend → plane is the one
            # permitted nesting order, and health() takes the plane lock).
            doc["serve"] = self.serve_plane.health()
        if self.federation is not None:
            # The federation view: peers + gossip ages, the slice map,
            # forwarded-op/parked counters, promotions in flight.
            doc["federation"] = self.federation.health()
        # Cost observatory digest (registry takes its own lock): program
        # counts, compile bill, storms, per-member warmth.
        doc["programs"] = self.programs.health_summary()
        return doc

    def _push_fed_peers(self) -> None:
        """Federation peer set changed: re-push the control re-home
        fallback list to every live worker (FED_PEERS), so workers that
        registered before the federation converged — or that outlive a
        peer loss — always hold current fallbacks."""
        fallbacks = self.federation.worker_fallbacks()
        for m in self.membership.alive_members():
            try:
                m.channel.send(
                    {"type": P.FED_PEERS, "peers": fallbacks}
                )
            except OSError:
                pass

    def _cluster_profile(self, seconds: Optional[float]) -> dict:
        """POST /profile: capture locally first — the rate limiter lives
        here, one knob for the whole cluster — then fan the same window to
        every live worker fire-and-forget (each lands its own artifact
        beside its crash dumps)."""
        result = self._profiler.capture(seconds)
        if not result.get("ok"):
            return result
        fanned = []
        for m in self.membership.alive_members():
            try:
                m.channel.send(
                    {"type": P.PROFILE, "seconds": result["seconds"]}
                )
                fanned.append(m.name)
            except OSError:
                pass
        result["members"] = sorted(fanned)
        return result

    def _io_loop(self) -> None:
        while True:
            item = self._io_queue.get()
            if item is None:
                self._io_queue.task_done()
                return
            kind, args = item
            try:
                if kind == "tile":
                    self.store.save_tile(*args)
                elif kind == "finalize":
                    epoch, rule, grid, shape, meta = args
                    self.store.finalize_epoch(
                        epoch, rule, grid, shape, meta=meta
                    )
            except Exception as e:  # any write failure: fail loudly, never
                # strand stop() on an unjoinable queue
                with self._lock:
                    self.error = f"checkpoint IO failed: {e!r}"
                self.done.set()
            finally:
                self._io_queue.task_done()

    def wait_for_backends(self, timeout: Optional[float] = None) -> bool:
        """Reference semantics: give workers ``wait-for-backends`` to join
        (``Run.scala:50``), but start as soon as the quorum is there."""
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.config.wait_for_backends_s
        )
        while time.monotonic() < deadline:
            if len(self.membership.alive_members()) >= self.min_backends:
                return True
            time.sleep(0.01)
        return len(self.membership.alive_members()) >= self.min_backends

    def start_simulation(self) -> None:
        with self._lock:
            members = self.membership.placeable_members()
            if len(members) < self.min_backends:
                raise RuntimeError(
                    f"only {len(members)} backends joined, need {self.min_backends}"
                )
            # Oversubscription: tiles_per_worker > 1 deals several tiles to
            # each worker (round-robin below), giving the coalescing data
            # plane multiple rings per peer per epoch to batch into one
            # frame and node-loss recovery finer redistribution units.
            self.layout = layout_for_workers(
                self.config.shape,
                len(members) * self.config.tiles_per_worker,
            )
            th, tw = self.layout.tile_shape
            tile_bytes = th * tw // 8 if self.rule.states == 2 else th * tw
            if tile_bytes > MAX_FRAME - (1 << 20):
                raise RuntimeError(
                    f"a {th}x{tw} tile needs ~{tile_bytes} wire bytes, over "
                    f"the {MAX_FRAME}-byte frame cap — run more workers so "
                    "tiles shrink"
                )
            if min(th, tw) < self.config.exchange_width:
                raise RuntimeError(
                    f"exchange_width={self.config.exchange_width} exceeds the "
                    f"{th}x{tw} tile — a ring cannot be wider than its tile"
                )
            epoch0, tiles0 = self._load_recovery_tiles_locked()
            self._last_ckpt = (epoch0, tiles0)
            self.start_epoch = epoch0
            self.observer.set_cluster_layout(
                len(self.layout.tile_ids), self.config.shape
            )
            if self.config.probe_window is not None:
                y0, y1, x0, x1 = self.config.probe_window
                th2, tw2 = self.layout.tile_shape
                n_hit = sum(
                    1
                    for t in self.layout.tile_ids
                    if (oy := t[0] * th2) < y1
                    and oy + th2 > y0
                    and (ox := t[1] * tw2) < x1
                    and ox + tw2 > x0
                )
                self.observer.expect_window(self.config.probe_window, n_hit)

            if self.config.tick_s > 0:
                # Paced mode: announce epochs one tick at a time, like the
                # reference's fixed 3 s clock (BoardCreator.scala:107).
                self.target_epoch = epoch0
                self._next_tick = time.monotonic() + self.config.start_delay_s
            else:
                # Free-running: announce the final target; tiles pipeline
                # toward it asynchronously, epoch-tagged (the reference's
                # lag-and-catch-up behavior, CellActor.scala:41-47).
                self.target_epoch = self.config.max_epochs

            if self.config.fault_injection.enabled:
                self.injector = CrashInjector(
                    self.config.fault_injection,
                    registry=self.metrics,
                    flight=self.tracer.flight,
                )

            # Root the run's trace: every backend step/halo/recovery span
            # links back here through the context TICK/DEPLOY carry.
            self._run_span = self.tracer.start(
                "cluster.run", node="frontend",
                shape=str(self.config.shape), max_epochs=self.config.max_epochs,
                members=len(members),
            )
            self._epoch_span = self.tracer.start(
                "epoch", parent=self._run_span, node="frontend",
                target=self.target_epoch,
            )

            assignments: Dict[str, List[TileId]] = {m.name: [] for m in members}
            for idx, tile in enumerate(self.layout.tile_ids):
                m = members[idx % len(members)]
                assignments[m.name].append(tile)
                self.tile_owner[tile] = m.name
                self.tile_epochs[tile] = epoch0
            # Wiring before data: workers must know every tile's peer
            # address before the first DEPLOY makes them publish rings.
            self._broadcast_owners_locked()
            for m in members:
                m.tiles = assignments[m.name]
            self._started.set()
        # Bulk sends outside the lock (see _send_deploy).
        for m in members:
            if m.tiles:
                self._send_deploy(m, m.tiles)

    def _owners_msg_locked(self) -> dict:
        """The current wiring as one OWNERS message.  Caller holds the lock."""
        rows = []
        for tile, owner in self.tile_owner.items():
            m = self.membership.get(owner)
            if m is None:
                continue
            rows.append([list(tile), owner, m.peer_host, m.peer_port])
        return {
            "type": P.OWNERS,
            "tiles": rows,
            "grid": list(self.layout.grid),
            "shape": list(self.config.shape),
        }

    def _broadcast_owners_locked(self) -> None:
        """NeighboursRefs (re-)wiring (BoardCreator.scala:86-88,149-151):
        every worker learns every tile's owner and peer data-plane address.
        The frontend brokers addresses only — ring bytes never touch it."""
        msg = self._owners_msg_locked()
        for m in self.membership.alive_members():
            self._safe_send(m, msg)

    def _load_recovery_tiles_locked(self) -> Tuple[int, Dict[TileId, dict]]:
        """The (epoch, packed tile dict) the run starts/recovers from.

        A durable per-tile checkpoint whose grid matches the current layout
        is loaded tile-by-tile — the full board never materializes; a
        full-board (or grid-mismatched) checkpoint is split and re-packed;
        otherwise the deterministic initial board is split and packed."""
        layout = self.layout
        if self.store is not None and self.store.latest_epoch() is not None:
            epoch0 = self.store.latest_epoch()
            meta = getattr(self.store, "tile_meta", None)
            if meta is not None:
                try:
                    epoch_meta = self.store.tile_meta(epoch0)
                    if tuple(epoch_meta["grid"]) == layout.grid:
                        # Stored payloads go straight back onto the wire —
                        # no unpack/repack, no full-tile materialization.
                        tiles = {
                            t: self.store.load_tile_payload(epoch0, t)
                            for t in layout.tile_ids
                        }
                        self._certify_recovery_tiles_locked(epoch_meta, tiles)
                        # One restore per recovery-source load: this path
                        # bypasses store.load(), so count it here (the
                        # full-board fallback below counts inside load()).
                        self.store.metrics.restores.inc()
                        return epoch0, tiles
                except FileNotFoundError:
                    pass  # latest is a full-board file; fall through
            ckpt = self.store.load()
            board, epoch0 = ckpt.board, ckpt.epoch
        else:
            epoch0 = 0
            board = initial_board(self.config)
        return epoch0, {
            t: pack_tile(layout.extract(board, t)) for t in layout.tile_ids
        }

    def _certify_recovery_tiles_locked(
        self, epoch_meta: dict, tiles: Dict[TileId, dict]
    ) -> None:
        """Certify a per-tile recovery source against the 64-bit digest its
        finalize recorded (present when the saving run had obs_digest on):
        re-derive per-tile lanes from the payloads — one tile at a time,
        no board assembly — merge, and fail LOUDLY on mismatch.  A corrupt
        checkpoint deployed as a recovery source would otherwise fork the
        whole cluster's trajectory silently."""
        from akka_game_of_life_tpu.ops import digest as odigest

        recorded = epoch_meta.get("digest")
        if not recorded:
            return
        t0 = time.perf_counter()
        computed = odigest.format_digest(
            odigest.value(
                odigest.merge_lanes(
                    odigest.digest_payload_np(
                        payload, self.layout.origin(t), self.config.width
                    )
                    for t, payload in tiles.items()
                )
            )
        )
        self._m_digest_checks.inc()
        self._m_digest_seconds.observe(time.perf_counter() - t0)
        if computed != recorded:
            self._m_digest_mismatches.inc()
            self.events.emit(
                "digest_mismatch",
                epoch=int(epoch_meta.get("epoch", -1)),
                stored=recorded,
                computed=computed,
            )
            raise ValueError(
                f"recovery checkpoint failed digest certification: stored "
                f"{recorded}, computed {computed} — refusing to deploy a "
                f"corrupt recovery source"
            )

    def _send_deploy(
        self,
        member: Member,
        tiles: List[TileId],
        *,
        state_epoch: Optional[int] = None,
        payloads: Optional[Dict[TileId, dict]] = None,
        ring_history: Optional[Dict[TileId, list]] = None,
    ) -> None:
        """Ship tiles to a worker.  Callers must NOT hold the frontend lock:
        a DEPLOY is a multi-megabyte send, and the receiving worker may be
        deep in a multi-second compute step, not reading — a blocking send
        under the global lock would stall every reader thread behind it and
        auto-down live workers (the bulk-send liveness hazard).

        By default the recovery (epoch, payload) pair is read HERE, under
        one lock acquisition: a caller passing an epoch it read earlier
        races with a checkpoint completing in between, shipping a newer
        board labeled with the older epoch — the tile then replays from a
        wrong state and silently corrupts the trajectory (caught by the
        width-k node-loss test, where chunked stepping makes
        kill-during-checkpoint likely).

        A live migration instead passes the certified ``payloads`` at their
        frozen ``state_epoch`` (plus ``ring_history``, the source's retained
        rings for the tile, forwarded in-band so the destination can serve
        lagging neighbors even after the source has left the wiring) — the
        tile resumes exactly where it froze, no checkpoint replay."""
        with self._lock:
            now = time.monotonic()
            if payloads is not None:
                # A migration deploy races member loss: if the destination
                # died (or a tile was re-placed by recovery) between COMMIT
                # and this send, mutating the bookkeeping below would pin
                # the tile's epoch at the frozen value while its real owner
                # replays from a checkpoint — and the wrongly-high prune
                # floor would drop ring history the replay still needs
                # (PROGRESS is monotone-max, so it never self-corrects).
                # The recovery path already owns the tile; drop this deploy.
                if not member.alive or any(
                    self.tile_owner.get(t) != member.name for t in tiles
                ):
                    return
                epoch, recovery = state_epoch, payloads
            else:
                epoch, recovery = self._last_ckpt
            for t in tiles:
                # A freshly deployed tile gets a full stuck_timeout_s of
                # grace before GATHER_FAILED may count it as wedged.
                self._last_ring_time[t] = now
                # Keep the lag/prune bookkeeping consistent with the epoch
                # actually shipped (not one a caller read before the swap).
                self.tile_epochs[t] = epoch
            specs = []
            for t in tiles:
                spec = {
                    "id": list(t),
                    "epoch": epoch,
                    "origin": list(self.layout.origin(t)),
                    "state": recovery[t],  # bit-packed, straight to wire
                }
                if ring_history and ring_history.get(t):
                    spec["rings"] = ring_history[t]
                specs.append(spec)
            msg = {
                "type": P.DEPLOY,
                "tiles": specs,
                "rule": self.rule.rulestring(),
                "target": self.target_epoch,
                "final_epoch": self.config.max_epochs,
                "render_every": self.config.render_every,
                "render_strides": list(self.observer.render_strides),
                "checkpoint_every": self._ckpt_cadence,
                "metrics_every": self.config.metrics_every,
            }
            if self.config.probe_window is not None:
                # Workers attach their tile∩window cells to render-cadence
                # TILE_STATE pushes; the observer stitches the exact window
                # (O(window) on the wire at any board size).
                msg["probe_window"] = list(self.config.probe_window)
            attach_trace(msg, self._epoch_span)
        with self.tracer.span(
            "cluster.deploy", parent=self._epoch_span, node="frontend",
            member=member.name, tiles=len(tiles), epoch=epoch,
        ):
            self._safe_send(member, msg)

    def _safe_send(self, member: Member, msg: dict) -> None:
        try:
            member.channel.send(msg)
        except OSError:
            self._on_member_lost(member.name)

    def stop(self) -> None:
        self._stop.set()
        handoff = False
        if self.federation is not None:
            # Computed BEFORE close() clears the peer table: are there
            # live peers this frontend's workers can re-home to?
            handoff = bool(self.federation.worker_fallbacks())
            # Before the plane closes: peer links drop cleanly (survivors
            # see EOF + a refused redial and promote — the rolling-restart
            # discipline), and no forwarded op can land on a closed plane.
            self.federation.close()
        if self.serve_plane is not None:
            # Before SHUTDOWN frames: pending tenant ops fail fast with
            # "router is closed" instead of timing out against workers
            # that are about to leave.
            self.serve_plane.close()
        for m in self.membership.alive_members():
            if handoff:
                # Rolling-restart discipline: leave the serve workers
                # RUNNING.  A SHUTDOWN would take every session this
                # frontend owns down with it; an abrupt close instead
                # makes the worker re-home (state intact) to a surviving
                # peer via its FED_PEERS fallbacks and announce
                # SHARD_HOME there — the same path a kill -9 exercises.
                try:
                    m.channel.close()
                except OSError:
                    pass
                continue
            try:
                m.channel.send({"type": P.SHUTDOWN})
            except OSError:
                pass
        if self.config.trace_file:
            # Drain the workers' final P.SPANS batches before the export
            # below: each worker flushes its pending spans on SHUTDOWN
            # receipt and then closes, and its reader thread here ingests
            # everything sent before the EOF — so "every member gone" means
            # the tail has landed.  Bounded: a wedged worker costs 2 s, not
            # the shutdown.
            deadline = time.monotonic() + 2.0
            while self.membership.alive_members() and time.monotonic() < deadline:
                time.sleep(0.01)
        try:
            # shutdown() before close(): the accept-loop thread blocked in
            # accept() holds a kernel reference to the listening socket, so
            # close() alone leaves the port accepting (and the redial-refused
            # death confirmation peers rely on never fires).
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        # Drain queued checkpoint writes, then stop the IO thread.
        self._io_queue.join()
        self._io_queue.put(None)
        if self.store is not None:
            # Async (orbax) saves must be durable before the process exits.
            self.store.close()
        # Observability epilogue: close out the run's spans, final
        # exposition + trace dumps, then tear the live endpoint and the
        # event log down (a scrape after stop() would show a half-dead
        # cluster).  Every step is failure-contained so teardown completes.
        with self._lock:
            # Under the lock: the paced-mode rotation also runs under it
            # (and skips once _stop is set), so the span finished here is
            # always the last one minted.
            if self._degraded_span is not None:
                self._degraded_span.set(healed=False).finish()
                self._degraded_span = None
            # Elastic-plane spans must not outlive the run: migrations and
            # drains still open at stop() finish with outcome=shutdown.
            for mig in list(self.rebalancer.inflight.values()):
                if mig.span is not None:
                    mig.span.set(outcome="shutdown").finish()
                    mig.span = None
            for span in self._drain_spans.values():
                span.set(outcome="shutdown").finish()
            self._drain_spans.clear()
            if self._epoch_span is not None:
                self._epoch_span.set(done=self.done.is_set()).finish()
            if self._run_span is not None:
                self._run_span.set(error=self.error).finish()
        if self._metrics_dumper is not None:
            self._metrics_dumper.final()
        if self.config.trace_file:
            try:
                self.tracer.write(self.config.trace_file)
            except Exception as e:  # noqa: BLE001 — teardown must complete
                print(f"trace-file write failed: {e!r}", flush=True)
        if self._metrics_server is not None:
            self._metrics_server.close()
            self._metrics_server = None
        if self._serve_slo is not None:
            self._serve_slo.close()
            self._serve_slo = None
        with self._lock:
            err = self.error
        self.events.emit(
            "frontend_stopped",
            error=err,
            done=self.done.is_set(),
        )
        self.events.close()

    # -- pause/resume (reachable, unlike BoardCreator.scala:109-112) ---------

    def pause(self) -> None:
        with self._lock:
            self.paused = True
            for m in self.membership.alive_members():
                self._safe_send(m, {"type": P.PAUSE})

    def resume(self) -> None:
        with self._lock:
            self.paused = False
            for m in self.membership.alive_members():
                self._safe_send(m, {"type": P.RESUME})

    # -- accept / per-connection reader --------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            channel = Channel(sock, send_deadline_s=self.config.send_deadline_s)
            if self.netchaos is not None and self.netchaos.config.wraps_control:
                # Control-plane chaos drops silently: a cut control link is
                # judged by heartbeats/eviction, not by send exceptions.
                # dst is labeled once REGISTER names the worker.
                channel = wrap_channel(channel, self.netchaos, src="frontend")
            t = threading.Thread(
                target=self._serve_connection, args=(channel,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_connection(self, channel: Channel) -> None:
        member: Optional[Member] = None
        try:
            hello = channel.recv()
            if (
                isinstance(hello, dict)
                and hello.get("type") == P.P_HELLO
                and self.federation is not None
            ):
                # A peer FRONTEND dialed the worker listener: the federation
                # peer plane shares this port (one address to seed, one
                # firewall rule).  serve_peer answers the handshake and
                # becomes this connection's reader until EOF.
                self.federation.serve_peer(channel, hello)
                return
            # The listener is an open TCP port: a hello that is not a
            # well-typed REGISTER (port scan, wrong peer, wrong version) is
            # closed without ceremony — and without a thread traceback.
            if (
                not isinstance(hello, dict)
                or hello.get("type") != P.REGISTER
                or not isinstance(hello.get("name"), (str, type(None)))
                or not isinstance(hello.get("peer_port", 0), int)
            ):
                channel.close()
                return
            engine = hello.get("engine", "jax")
            if self.config.exchange_width > 1 and str(engine).startswith("actor"):
                # Actor engines step per-epoch and cannot honor width-k
                # rings; a mixed-width cluster would deadlock on epochs the
                # chunked tiles never compute, so refuse at the door.
                print(
                    f"rejecting worker with engine={engine}: exchange_width="
                    f"{self.config.exchange_width} needs chunk-capable "
                    f"engines (numpy/jax)",
                    flush=True,
                )
                channel.send({"type": P.SHUTDOWN})
                channel.close()
                return
            try:
                peer_host = channel.sock.getpeername()[0]
            except OSError:
                peer_host = "127.0.0.1"
            member = self.membership.register(
                channel,
                hello.get("name"),
                peer_host=peer_host,
                peer_port=int(hello.get("peer_port", 0)),
            )
            if isinstance(channel, ChaosChannel):
                channel.dst = member.name
                self.netchaos.register_node(member.name)
            welcome_serve = {}
            if self.serve_plane is not None:
                from akka_game_of_life_tpu.serve.worker import serve_policy

                # The serve knobs are frontend-owned cluster policy, like
                # the ring/retry bundles: every worker builds its local
                # SessionRouter from the SAME source of truth.
                welcome_serve = {
                    "serve_cluster": True,
                    "serve": serve_policy(self.config),
                }
                if self.federation is not None:
                    # The control-channel re-home fallback list: the live
                    # peer frontends' worker listeners.  Also re-pushed as
                    # FED_PEERS whenever the peer set changes, so a worker
                    # that registered before the federation converged still
                    # learns its fallbacks.
                    welcome_serve["federation"] = (
                        self.federation.worker_fallbacks()
                    )
            channel.send(
                {
                    "type": P.WELCOME,
                    "name": member.name,
                    **welcome_serve,
                    "heartbeat_s": self.config.heartbeat_s,
                    "max_pull_retries": self.config.max_pull_retries,
                    "exchange_width": self.config.exchange_width,
                    # One retry/breaker/deadline policy source of truth for
                    # every worker of this cluster (SimulationConfig).
                    "retry_s": self.config.retry_s,
                    "retry_max_s": self.config.retry_max_s,
                    "breaker_failures": self.config.breaker_failures,
                    "breaker_cooldown_s": self.config.breaker_cooldown_s,
                    "send_deadline_s": self.config.send_deadline_s,
                    # Halo-plane wire policy: every worker of a cluster
                    # packs/batches identically (the negotiation — a worker
                    # never has to guess a peer's encoding, and the entries
                    # self-describe anyway, so a mismatch fails loud in
                    # decode_ring rather than mis-assembling halos).
                    "ring_pack": self.config.ring_pack,
                    "ring_batch": self.config.ring_batch,
                    "ring_queue_depth": self.config.ring_queue_depth,
                    # Digest plane: workers attach per-tile fingerprint
                    # lanes to PROGRESS at digest-due epochs when on.
                    "obs_digest": self.config.obs_digest,
                    # Quiescence tier (activity-gated sparse stepping):
                    # workers skip provably-repeating chunks and publish
                    # O(1)-byte same-ring markers when on.
                    "sparse_cluster": self.config.sparse_cluster,
                    # Compile & cost observatory: ledger on/off, COST frame
                    # cadence, profiler-capture policy — one source of
                    # truth for every member's program accounting.
                    "obs": {
                        "programs": self.config.obs_programs,
                        "cost_interval_s": self.config.obs_cost_interval_s,
                        "max_s": self.config.obs_profile_max_s,
                        "min_interval_s": self.config.obs_profile_min_interval_s,
                    },
                }
            )
            engine = hello.get("engine", "?")
            detail = (
                f" (engine {engine}, pallas {hello.get('pallas', 'auto')})"
                if engine == "jax"
                else f" (engine {engine})"
            )
            print(f"backend {member.name} joined{detail}", flush=True)
            self._m_joined.inc()
            self._m_alive.set(len(self.membership.alive_members()))
            self.events.emit(
                "member_joined", member=member.name, engine=str(engine)
            )
            if self.serve_plane is not None:
                # The plane claims unowned shards for a first worker; a
                # late joiner receives its shards through the rebalancer
                # (empty shards flip instantly on the next poll).
                self.serve_plane.on_member_joined(member.name)
            with self._lock:
                late = self._started.is_set() and self.layout is not None
                if late:
                    # Late join (after start_simulation): the deterministic
                    # path is admit-and-idle — the member gets the current
                    # wiring immediately (it can dial peers, serve pulls,
                    # and is a valid migration destination from this
                    # moment) and hosts no tiles until the rebalancer
                    # migrates load onto it.  Scale-out is exactly this
                    # plus a migration.  Sent UNDER the lock, like every
                    # _broadcast_owners_locked call site: a migration committing
                    # concurrently must not slot its OWNERS broadcast
                    # between this snapshot and its send — the stale
                    # snapshot arriving last would make the joiner drop a
                    # tile just migrated onto it.
                    self._safe_send(member, self._owners_msg_locked())
            while True:
                msg = channel.recv()
                if msg is None:
                    break
                if self._stop.is_set():
                    # Post-stop: drain ONLY the workers' final span batches
                    # (flushed on SHUTDOWN receipt, just before their EOF —
                    # and possibly queued behind a last heartbeat/progress
                    # frame); everything else from a stopping cluster is
                    # stale.  Looping to EOF is what makes stop()'s
                    # "members gone ⇒ tail ingested" drain wait sound.
                    if (
                        isinstance(msg, dict)
                        and msg.get("type") == P.SPANS
                        and isinstance(msg.get("spans"), list)
                    ):
                        self.tracer.ingest(msg["spans"])
                    continue
                try:
                    # Validate structure BEFORE dispatch: a malformed message
                    # drops the worker with a one-line reason (tiles
                    # redeploy), while a bug inside _dispatch itself still
                    # surfaces as a real traceback instead of being
                    # misattributed to the worker.
                    _validate_msg(msg)
                except MalformedMessage as e:
                    print(f"dropping {member.name}: {e}", flush=True)
                    break
                self._dispatch(member, msg)
        except (OSError, ValueError) as e:
            if member is not None and isinstance(e, ValueError):
                # A malformed FRAME (bad magic / oversize / bad payload
                # structure, raised by wire.recv) gets the same one-line
                # drop note the malformed-MESSAGE path prints.
                print(f"dropping {member.name}: {e}", flush=True)
        finally:
            if member is not None:
                self._on_member_lost(member.name)

    # -- message handling ----------------------------------------------------

    def _dispatch(self, member: Member, msg: dict) -> None:
        # Any traffic is proof of life — a worker mid-burst on bulk sends
        # may have its HEARTBEAT frames queued behind megabytes of
        # TILE_STATE, and must not be auto-downed for it.
        self.membership.beat(member.name)
        kind = msg.get("type")
        if kind == P.HEARTBEAT:
            pass
        elif kind == P.SPANS:
            # Worker-forwarded finished spans: fold them into this tracer so
            # --trace-file / /trace export the cluster-wide causal timeline
            # from one process (ids are verbatim, so parent links to the
            # epoch spans we minted here just work).
            spans = msg.get("spans")
            if isinstance(spans, list):
                self.tracer.ingest(spans)
        elif kind == P.COST:
            # Worker program-ledger summary: fold into the cluster-merged
            # /programs + /cost view and the member-labeled device gauges.
            self.programs.merge_remote(member.name, msg)
        elif kind == P.PROGRESS:
            # Control-plane ping only — ring bytes ride worker-to-worker
            # (PEER_RING); the frontend just tracks lag for the prune floor
            # and the stuck detector.
            tile = tuple(msg["tile"])
            epoch = int(msg["epoch"])
            inject_due = False
            with self._lock:
                if self.tile_owner.get(tile) != member.name:
                    return  # stale ping from an evicted owner
                self.tile_epochs[tile] = max(self.tile_epochs.get(tile, 0), epoch)
                self._last_ring_time[tile] = time.monotonic()
                q = msg.get("q")
                if isinstance(q, int):
                    if q > 0:
                        self.quiescent[tile] = q
                    else:
                        self.quiescent.pop(tile, None)
                    self._m_tiles_quiescent.set(len(self.quiescent))
                skipped = msg.get("skipped")
                if isinstance(skipped, int) and skipped > 0:
                    # Worker-reported delta of chunks it skipped outright —
                    # the cluster tier's headline counter.
                    self._m_tiles_skipped.inc(skipped)
                if "digest" in msg:
                    self._note_tile_digest_locked(tile, epoch, msg["digest"])
                if (
                    self.injector is not None
                    and self.injector.config.epoch_indexed
                    and self._started.is_set()
                    and self.layout is not None
                ):
                    # Epoch-anchored chaos: the schedule is indexed by the
                    # slowest tile's progress, so a crash due at epoch E
                    # fires before the run can complete past E — no race
                    # against the wall clock.  Evaluated under the lock so
                    # concurrent member threads cannot double-fire one slot.
                    floor = min(
                        (self.tile_epochs.get(t, 0) for t in self.layout.tile_ids),
                        default=0,
                    )
                    inject_due = self.injector.should_crash_at_epoch(floor)
            if inject_due:
                self._inject_crash()
        elif kind == P.TILE_STATE:
            self._on_tile_state(member, msg)
        elif kind == P.REDEPLOY_REQUEST:
            tile = tuple(msg["tile"])
            self._redeploy_tile(tile, preferred=member.name)
        elif kind == P.GATHER_FAILED:
            self._on_gather_failed(member, tuple(msg["tile"]), int(msg["epoch"]))
        elif kind == P.MIGRATE_STATE:
            self._on_migrate_state(member, msg)
        elif kind == P.SERVE_RESULT:
            if self.serve_plane is not None:
                self.serve_plane.on_result(member.name, msg)
        elif kind == P.SHARD_STATE:
            if self.serve_plane is not None:
                self.serve_plane.on_shard_state(member.name, msg)
        elif kind == P.SHARD_REPLICATE:
            if self.serve_plane is not None:
                self.serve_plane.on_shard_replicate(member.name, msg)
        elif kind == P.SHARD_HOME:
            # A worker re-homed its control channel here after its previous
            # frontend died: its session list is the truth that closes the
            # federation failover window.
            if self.serve_plane is not None:
                self.serve_plane.on_shard_home(member.name, msg)
        elif kind == P.DRAIN_REQUEST:
            self._on_drain_request(member)
        elif kind == P.GOODBYE:
            self._on_member_lost(member.name)

    def _on_tile_state(self, member: Member, msg: dict) -> None:
        """Scale-safe state sink: checkpoint/final tiles arrive bit-packed
        and stream straight to the per-tile store (never assembled), render
        arrives as the frontend's strided sample, metrics as a population
        count — nothing here is O(board) in memory or on the wire."""
        tile = tuple(msg["tile"])
        epoch = int(msg["epoch"])
        reasons = msg.get("reasons", [])
        with self._lock:
            if self.tile_owner.get(tile) != member.name:
                return
            durable = self.store is not None and bool(self.config.checkpoint_every)
            if "final" in reasons and epoch == self.config.max_epochs:
                self._final_tiles[tile] = msg["state"]
                if self.store is not None:
                    self._io_queue.put(("tile", (epoch, tile, msg["state"])))
                if len(self._final_tiles) == len(self.layout.tile_ids):
                    if self.store is not None:
                        self._io_queue.put(
                            (
                                "finalize",
                                (
                                    epoch,
                                    self.rule.rulestring(),
                                    self.layout.grid,
                                    self.config.shape,
                                    self._digest_meta_locked(epoch),
                                ),
                            )
                        )
                    h, w = self.config.shape
                    if h * w <= _ASSEMBLE_LIMIT:
                        self.final_board = self._assemble_locked(self._final_tiles)
                    self.done.set()
            if (
                "checkpoint" in reasons
                and epoch > self._last_ckpt[0]  # a replaying tile re-reports
                # epochs already saved; don't recreate pending entries that
                # can never complete
            ):
                pend = self._ckpt_pending.setdefault(epoch, {})
                pend[tile] = msg["state"]
                if durable:
                    self._io_queue.put(("tile", (epoch, tile, msg["state"])))
                if len(pend) == len(self.layout.tile_ids):
                    if durable:
                        # An explicit cadence means durable saves; the
                        # fallback cadence checkpoints in memory only (the
                        # store still gets the final board).
                        self._io_queue.put(
                            (
                                "finalize",
                                (
                                    epoch,
                                    self.rule.rulestring(),
                                    self.layout.grid,
                                    self.config.shape,
                                    self._digest_meta_locked(epoch),
                                ),
                            )
                        )
                    self._last_ckpt = (epoch, pend)
                    # Older pending epochs can no longer become the recovery
                    # point; drop them along with this one.
                    for e in [e for e in self._ckpt_pending if e <= epoch]:
                        del self._ckpt_pending[e]
                    # Bounded history: broadcast a prune floor so workers
                    # drop rings no tile can ever need again.  The floor is
                    # the *slowest* tile, not the checkpoint epoch — a tile
                    # redeployed from an older checkpoint may still be
                    # replaying epochs below this checkpoint, and pruning
                    # those rings would stall its replay forever (a race
                    # found by the node-loss test).
                    floor = min(
                        [epoch] + [self.tile_epochs[t] for t in self.layout.tile_ids]
                    )
                    for m in self.membership.alive_members():
                        self._safe_send(m, {"type": P.PRUNE, "floor": floor})
            if "render" in reasons:
                self.observer.add_sample(
                    epoch, tile, tuple(msg["scaled_origin"]), msg["sample"]
                )
                if "window" in msg:
                    self.observer.add_window(
                        epoch, tile, tuple(msg["window_origin"]), msg["window"]
                    )
            if "metrics" in reasons:
                self.observer.add_population(epoch, tile, int(msg["population"]))

    def _digest_meta_locked(self, epoch: int) -> Optional[dict]:
        """Checkpoint metadata carrying the epoch's merged digest, or None.
        The merge always completes before the finalize enqueue: each
        tile's PROGRESS (with lanes) precedes its TILE_STATE on the same
        channel, and the finalize fires on the LAST tile's state.  Caller
        holds the lock."""
        from akka_game_of_life_tpu.ops import digest as odigest

        if epoch not in self.epoch_digests:
            return None
        return {"digest": odigest.format_digest(self.epoch_digests[epoch])}

    def _note_tile_digest_locked(self, tile: TileId, epoch: int, lanes) -> None:
        """One tile's digest lanes from a PROGRESS ping; when every tile of
        the epoch has reported, fold them (lane-wise uint32 sum — the same
        merge rule as the mesh ``psum``) into the epoch's 64-bit value.
        O(tiles) bytes total; the board is never assembled.  Re-reports
        from replaying/redeployed tiles are recognized by the monotone
        completion floor (the ``_complete_epoch`` discipline).  Caller
        holds the lock."""
        from akka_game_of_life_tpu.ops import digest as odigest

        if self.layout is None or (
            self._digest_floor is not None and epoch <= self._digest_floor
        ):
            return
        t0 = time.perf_counter()
        parts = self._digest_partial.setdefault(epoch, {})
        parts[tile] = (int(lanes[0]), int(lanes[1]))
        if len(parts) < len(self.layout.tile_ids):
            return
        del self._digest_partial[epoch]
        self._digest_floor = epoch
        for e in [e for e in self._digest_partial if e <= epoch]:
            del self._digest_partial[e]
        merged = odigest.value(odigest.merge_lanes(parts.values()))
        self.epoch_digests[epoch] = merged
        while len(self.epoch_digests) > 16:  # bounded: certificates, not history
            del self.epoch_digests[min(self.epoch_digests)]
        if epoch == self.config.max_epochs:
            self.final_digest = merged
        hexd = odigest.format_digest(merged)
        self._m_digest_checks.inc()
        self._m_digest_seconds.observe(time.perf_counter() - t0)
        with self.tracer.span(
            "obs.digest", parent=self._epoch_span, node="frontend",
            epoch=epoch, digest=hexd, tiles=len(parts),
        ):
            self.events.emit("digest", epoch=epoch, digest=hexd)
        print(f"epoch {epoch}: digest={hexd}", file=self.observer.out, flush=True)

    def _assemble_locked(self, tiles: Dict[TileId, dict]) -> np.ndarray:
        from akka_game_of_life_tpu.runtime.tiles import stitch

        return stitch(
            {self.layout.origin(t): unpack_tile(p) for t, p in tiles.items()}
        )

    def _on_gather_failed(self, member: Member, tile: TileId, epoch: int) -> None:
        """FailedToGatherInfoMsg analog (NextStateCellGathererActor.scala:49-58):
        a tile's halo pulls have gone unanswered past the retry budget.  The
        reporting tile keeps its state; the *parent* repairs the neighborhood
        by redeploying any blocking neighbor that is genuinely stuck — behind
        the requested epoch AND silent (no ring push) for stuck_timeout_s.
        A neighbor that is merely slow keeps its progress and its lease."""
        with self._lock:
            if self.tile_owner.get(tile) != member.name or self.layout is None:
                return
            if (
                self.degraded
                and self.netchaos is not None
                and self.netchaos.partitioned()
            ):
                # A KNOWN partition (the injected chaos plane is
                # self-announcing): redeploying blocked neighbors would
                # thrash the restart budget without making any halo arrive —
                # wait for the heal instead.  A stall with no announced
                # partition keeps this recovery path (a wedged-but-alive
                # worker's tiles MUST move to healthy members; an external
                # partition is then guarded by the restart budget).
                return
            now = time.monotonic()
            stuck = [
                (ntile, self.tile_owner.get(ntile))
                for ntile in sorted(set(self.layout.neighbors(tile).values()))
                if ntile != tile
                and ntile not in self.rebalancer.inflight  # frozen on purpose
                and not self._quiescent_fresh_locked(ntile, now)  # silent on purpose
                and self.tile_epochs.get(ntile, 0) < epoch
                and now - self._last_ring_time.get(ntile, now)
                > self.config.stuck_timeout_s
            ]
        # (tile, owner) snapshotted under the lock above: reading the owner
        # here would race a migration commit and aim `avoid` at the NEW
        # owner, letting the redeploy land back on the wedged member.
        for ntile, owner in stuck:
            self._redeploy_tile(ntile, avoid=owner)

    # -- elastic plane: live migration, scale-out, drain ---------------------

    def _rebalance_poll(self, now: float, drain_only: bool = False) -> None:
        """One maintenance pass of the elastic plane: expire overdue
        migrations, start newly planned ones, release finished drains.
        Suspended while degraded — a stalled cluster must heal, not
        reshape.  ``drain_only`` (the paused cluster) plans drain-driven
        moves but no load balancing."""
        with self._lock:
            if (
                not self._started.is_set()
                or self.layout is None
                or self.degraded
            ):
                return
            overdue = self.rebalancer.expired(now)
        for mig in overdue:
            self._abort_migration(mig, "deadline")
        started: List[Tuple[Migration, Member]] = []
        with self._lock:
            if self._stop.is_set() or self.done.is_set():
                return
            plans = self.rebalancer.plan(
                self.membership.alive_members(),
                self.tile_epochs,
                self.config.max_epochs,
                now,
                drain_only=drain_only,
            )
            for tile, source, dest in plans:
                pair = self._begin_migration_locked(tile, source, dest, now)
                if pair is not None:
                    started.append(pair)
        # PREPARE frames outside the lock (send discipline).
        for mig, src in started:
            self._send_migrate_prepare(mig, src)
        self._check_drains()

    def migrate_tile(self, tile: TileId, dest: str) -> bool:
        """Manually start a live migration of ``tile`` to member ``dest`` —
        the operator/embedder entry to the same three-phase protocol the
        automatic planner drives.  Returns False when the move is not
        currently startable (unknown/departed members, tile already in
        flight, dest draining, or dest already the owner)."""
        now = time.monotonic()
        with self._lock:
            tile = tuple(tile)
            source = self.tile_owner.get(tile)
            if source is None or source == dest or self.layout is None:
                return False
            pair = self._begin_migration_locked(tile, source, dest, now)
        if pair is None:
            return False
        self._send_migrate_prepare(*pair)
        return True

    def _begin_migration_locked(
        self, tile: TileId, source: str, dest: str, now: float
    ) -> Optional[Tuple[Migration, Member]]:
        """Validate and record one migration (caller holds the lock);
        returns (migration, source member) for the PREPARE send, or None."""
        src = self.membership.get(source)
        dst = self.membership.get(dest)
        if (
            src is None or not src.alive
            or dst is None or not dst.alive or dst.draining
            or self.tile_owner.get(tile) != source
            or tile in self.rebalancer.inflight
        ):
            return None
        mig = self.rebalancer.begin(tile, source, dest, now)
        mig.span = self.tracer.start(
            "migrate.tile", parent=self._epoch_span, node="frontend",
            tile=str(tile), source=source, dest=dest,
        )
        self.events.emit(
            "migration_started",
            tile=list(tile),
            source=source,
            dest=dest,
            seq=mig.seq,
        )
        return mig, src

    def _send_migrate_prepare(self, mig: Migration, src: Member) -> None:
        self._safe_send(
            src,
            {
                "type": P.MIGRATE_PREPARE,
                "tile": list(mig.tile),
                "seq": mig.seq,
                "deadline_s": self.rebalancer.deadline_s,
            },
        )

    def _on_migrate_state(self, member: Member, msg: dict) -> None:
        """TRANSFER → CERTIFY → COMMIT.  The payload is certified against
        the source-computed digest lanes BEFORE any ownership change: a
        corrupted transfer rolls back loudly (the source still owns the
        canonical state), never forks the trajectory.  Commit is the atomic
        OWNERS rewiring; the certified payload then deploys to the
        destination at its frozen epoch."""
        from akka_game_of_life_tpu.ops import digest as odigest

        tile = tuple(msg["tile"])
        epoch = int(msg["epoch"])
        seq = int(msg["seq"])
        with self._lock:
            mig = self.rebalancer.get(tile, seq)
            if (
                mig is None
                or mig.source != member.name
                or self.tile_owner.get(tile) != member.name
            ):
                return  # stale state frame from an aborted/unknown attempt
            origin = self.layout.origin(tile)
        # Certification outside the lock: a tile-sized unpack+digest must
        # not stall every reader thread behind the coordinator lock.
        t0 = time.perf_counter()
        lanes = odigest.digest_payload_np(
            msg["state"], origin, self.config.width
        )
        self._m_digest_checks.inc()
        self._m_digest_seconds.observe(time.perf_counter() - t0)
        if [int(lanes[0]), int(lanes[1])] != [int(v) for v in msg["digest"]]:
            self._m_digest_mismatches.inc()
            self.events.emit(
                "digest_mismatch",
                tile=list(tile),
                epoch=epoch,
                source=member.name,
            )
            self._abort_migration(mig, "digest_mismatch")
            return
        with self._lock:
            if self.rebalancer.get(tile, seq) is not mig:
                return  # aborted (deadline/member loss) while certifying
            dest = self.membership.get(mig.dest)
            if dest is None or not dest.alive or dest.draining:
                commit = False
            else:
                commit = True
                now = time.monotonic()
                self.rebalancer.complete(tile)
                self.tile_owner[tile] = dest.name
                if tile in member.tiles:
                    member.tiles.remove(tile)
                if tile not in dest.tiles:
                    dest.tiles.append(tile)
                self.tile_epochs[tile] = epoch
                self._last_ring_time[tile] = now
                if self.quiescent.pop(tile, None) is not None:
                    self._m_tiles_quiescent.set(len(self.quiescent))
                self._m_migrations.inc()
                self._m_migration_seconds.observe(now - mig.started)
                if mig.span is not None:
                    mig.span.set(outcome="commit", epoch=epoch).finish()
                self.events.emit(
                    "migration_committed",
                    tile=list(tile),
                    source=mig.source,
                    dest=dest.name,
                    epoch=epoch,
                )
                # Wiring before data, as everywhere: the OWNERS broadcast
                # IS the commit point — the source drops the tile on
                # receipt, every peer re-aims its ring pushes, and only
                # then does the state land on the destination.
                self._broadcast_owners_locked()
        if not commit:
            self._abort_migration(mig, "dest_lost")
            return
        print(
            f"tile {tile}: migrated {mig.source} -> {dest.name} at epoch "
            f"{epoch}",
            flush=True,
        )
        self._send_deploy(
            dest,
            [tile],
            state_epoch=epoch,
            payloads={tile: msg["state"]},
            ring_history={tile: msg.get("rings") or []},
        )
        self._check_drains()

    def _abort_migration(
        self, mig: Migration, reason: str, *, notify_source: bool = True
    ) -> None:
        """Roll a migration back: the source (which never dropped the tile)
        unfreezes and resumes; the tile cools down under the jittered
        backoff before the planner may retry it.  Always loud: a counter, a
        lifecycle event, and a flight dump — a rollback is a fault artifact
        even though no state was lost."""
        with self._lock:
            if self.rebalancer.get(mig.tile, mig.seq) is not mig:
                return  # already concluded
            self.rebalancer.abort(mig.tile, time.monotonic())
            self._m_migration_aborts.inc()
            if mig.span is not None:
                mig.span.set(outcome="abort", reason=reason).finish()
            self.events.emit(
                "migration_aborted",
                tile=list(mig.tile),
                source=mig.source,
                dest=mig.dest,
                reason=reason,
            )
        print(
            f"tile {mig.tile}: migration {mig.source} -> {mig.dest} "
            f"aborted ({reason})",
            flush=True,
        )
        self.tracer.flight.dump("migration_abort", node="frontend")
        if notify_source:
            src = self.membership.get(mig.source)
            if src is not None and src.alive:
                self._safe_send(
                    src, {"type": P.MIGRATE_ABORT, "tile": list(mig.tile)}
                )

    def _on_drain_request(self, member: Member) -> None:
        """A worker asks to leave gracefully.  With another placeable
        member present, mark it draining — the planner empties it and
        ``_check_drains`` releases it; with nowhere to put its tiles the
        drain is refused immediately (the worker falls back to the abrupt
        GOODBYE path) rather than left hanging."""
        with self._lock:
            others = [
                m
                for m in self.membership.placeable_members()
                if m.name != member.name
            ]
            # A serve-only cluster (serve plane, no simulation) honors
            # drains from the moment it serves — _started never fires.
            active = self._started.is_set() or self.serve_plane is not None
            if not active or not others:
                refused = True
            else:
                refused = False
                if not member.draining:
                    member.draining = True
                    self._drain_spans[member.name] = self.tracer.start(
                        "cluster.drain", parent=self._run_span,
                        node="frontend", member=member.name,
                        tiles=len(member.tiles),
                    )
                    self.events.emit(
                        "drain_requested",
                        member=member.name,
                        tiles=len(member.tiles),
                    )
                    print(
                        f"member {member.name} draining "
                        f"({len(member.tiles)} tiles)",
                        flush=True,
                    )
            self._m_draining.set(
                sum(
                    1
                    for m in self.membership.alive_members()
                    if m.draining
                )
            )
        if refused:
            self.events.emit("drain_refused", member=member.name)
            self._safe_send(
                member, {"type": P.DRAIN_COMPLETE, "drained": False}
            )
            return
        # A tileless worker (e.g. a spare) drains in zero moves.
        self._check_drains()

    def _check_drains(self) -> None:
        """Release every draining member that owns nothing and has no
        in-flight migration — the DRAIN_COMPLETE that lets it exit rc=0
        with the guarantee its departure redeploys nothing."""
        released: List[Member] = []
        with self._lock:
            for m in self.membership.alive_members():
                if not m.draining or m.drain_acked:
                    continue
                busy = any(
                    m.name in (mig.source, mig.dest)
                    for mig in self.rebalancer.inflight.values()
                )
                if m.tiles or busy:
                    continue
                if self.serve_plane is not None and (
                    not self.serve_plane.member_clear(m.name)
                ):
                    # Still owns session shards (or a shard move touches
                    # it): the serve analog of "owns tiles" — release only
                    # once its sessions have migrated off.
                    continue
                m.drain_acked = True
                self._m_drains.inc()
                span = self._drain_spans.pop(m.name, None)
                if span is not None:
                    span.set(outcome="drained").finish()
                self.events.emit("member_drained", member=m.name)
                released.append(m)
        for m in released:
            print(f"member {m.name} drained", flush=True)
            self._safe_send(m, {"type": P.DRAIN_COMPLETE, "drained": True})

    # -- failure handling / redeployment -------------------------------------

    def _on_member_lost(self, name: str) -> None:
        member = self.membership.mark_dead(name)
        if member is None:
            return
        self._m_lost.inc()
        self._m_alive.set(len(self.membership.alive_members()))
        self.events.emit(
            "member_lost", member=name, tiles=len(member.tiles)
        )
        try:
            member.channel.close()
        except OSError:
            pass
        # Elastic-plane hygiene: a departed member leaves no stale gauge
        # series, no open drain span, and no in-flight migration.  A dead
        # DESTINATION rolls its migrations back (the live source unfreezes
        # and resumes — no epoch lost); a dead SOURCE just clears the
        # record, and the normal checkpoint redeploy below recovers its
        # tiles, the frozen one included.
        self._m_hb_age.labels(member=name).set(0)
        # Cost-observatory hygiene: the member's ledger contribution and
        # every member:device gauge child it owned go with it.
        self.programs.forget_remote(name)
        with self._lock:
            span = self._drain_spans.pop(name, None)
            if span is not None:
                span.set(outcome="lost").finish()
            self._m_draining.set(
                sum(1 for m in self.membership.alive_members() if m.draining)
            )
            doomed = self.rebalancer.drop_member(name)
        if not (self._stop.is_set() or self.done.is_set()):
            # Mid-run only: at shutdown the in-flight records die with the
            # run (stop() already finished their spans) — aborting them
            # against departing workers would be teardown noise.
            for mig in doomed:
                self._abort_migration(
                    mig, "member_lost", notify_source=(mig.source != name)
                )
            if self.serve_plane is not None:
                # Serve-plane bookkeeping: in-flight ops answered, shard
                # ownership reassigned, gauges reclaimed (never under the
                # frontend lock — plane methods take their own).
                self.serve_plane.on_member_lost(name)
        if not self._started.is_set():
            return
        if self._stop.is_set() or self.done.is_set():
            # Orderly shutdown: workers dropping now is expected, not a
            # failure to recover from.
            return
        tiles = list(member.tiles)
        member.tiles = []
        if not tiles:
            return
        # A node loss with tiles to recover is exactly the moment a
        # post-mortem wants context for: dump the flight ring and trace the
        # whole redeploy under the epoch it interrupts.
        self.tracer.flight.dump("node_loss", node="frontend")
        with self.tracer.span(
            "member.lost", parent=self._epoch_span, node="frontend",
            member=name, tiles=len(tiles),
        ):
            survivors = self.membership.alive_members()
            if not survivors:
                with self._lock:
                    self.error = "all backends lost"
                self.done.set()
                return
            with self._lock:
                # Assign every orphaned tile first, then wire and deploy
                # once — one OWNERS broadcast carrying the final assignment,
                # not one per tile, and no intermediate state advertising
                # the dead member for not-yet-reassigned tiles.
                assigned: Dict[str, List[TileId]] = {}
                for idx, tile in enumerate(tiles):
                    m = self._assign_tile_locked(
                        tile, preferred=survivors[idx % len(survivors)].name
                    )
                    if m is None:
                        return  # budget/survivor escalation already set error
                    assigned.setdefault(m.name, []).append(tile)
                self._broadcast_owners_locked()
            # Bulk sends outside the lock (see _send_deploy).
            for owner, batch in assigned.items():
                m = self.membership.get(owner)
                if m is not None and m.alive:
                    self._send_deploy(m, batch)

    def _quiescent_fresh_locked(self, tile: TileId, now: float) -> bool:
        """Is ``tile`` self-reported quiescent AND recently heard from?
        Quiescent tiles ping only at cadence epochs, so they look silent to
        the stuck/degraded detectors — but the exemption is freshness-bound
        (2x stuck_timeout_s): a worker that wedges after marking its tiles
        quiescent loses the exemption and normal recovery takes over.
        Caller holds the lock."""
        return (
            tile in self.quiescent
            and now - self._last_ring_time.get(tile, 0.0)
            <= 2.0 * self.config.stuck_timeout_s
        )

    def _assign_tile_locked(
        self,
        tile: TileId,
        preferred: Optional[str] = None,
        avoid: Optional[str] = None,
    ) -> Optional[Member]:
        """Pick (and record) a new owner for a tile, enforcing the restart
        budget — the reference's supervision strategy
        (``OneForOneStrategy(Restart, ≤10 retries/min)``,
        ``BoardCreator.scala:42-45``): a tile that keeps dying escalates to
        a run failure instead of redeploy-thrashing forever.  Returns None
        when escalation fired.  Caller holds the lock."""
        now = time.monotonic()
        times = self._redeploy_times.setdefault(tile, deque())
        while times and now - times[0] > self.config.restart_window_s:
            times.popleft()
        if len(times) >= self.config.restart_max:
            self.error = (
                f"tile {tile} exceeded its restart budget "
                f"({self.config.restart_max} redeploys in "
                f"{self.config.restart_window_s:.0f}s); escalating"
            )
            self.done.set()
            return None
        times.append(now)
        member = self.membership.get(preferred) if preferred else None
        if member is None or not member.alive or member.draining:
            # Placeable members first — a draining worker must not be
            # handed recovery work it would immediately hand back — but a
            # draining survivor still beats failing the run.
            survivors = (
                self.membership.placeable_members()
                or self.membership.alive_members()
            )
            if not survivors:
                self.error = "all backends lost"
                self.done.set()
                return None
            # Prefer moving off the current (possibly wedged) owner.
            others = [m for m in survivors if m.name != avoid]
            member = (others or survivors)[0]
        if tile not in member.tiles:
            member.tiles.append(tile)
        # Counted HERE, after every escalation/no-survivor early return: an
        # aborted reassignment redeployed nothing and must not read as
        # recovery activity.
        self._m_redeploys.inc()
        # The supervision-replay span: the recovery decision itself, linked
        # under the epoch it interrupts (the deploy that ships the state is
        # its sibling cluster.deploy span).
        with self.tracer.span(
            "recover.redeploy", parent=self._epoch_span, node="frontend",
            tile=str(tile), owner=member.name, epoch=self._last_ckpt[0],
        ):
            self.events.emit(
                "tile_redeploy",
                tile=list(tile),
                owner=member.name,
                epoch=self._last_ckpt[0],
            )
        self.tile_owner[tile] = member.name
        # A re-placed tile starts with no quiescence history; the marking
        # (and its stuck-exemption) must not survive the move.
        if self.quiescent.pop(tile, None) is not None:
            self._m_tiles_quiescent.set(len(self.quiescent))
        # The tile restarts at the recovery epoch: record that so the
        # ring-prune floor protects every epoch its replay will pull.
        self.tile_epochs[tile] = self._last_ckpt[0]
        return member

    def _redeploy_tile(
        self,
        tile: TileId,
        preferred: Optional[str] = None,
        avoid: Optional[str] = None,
    ) -> None:
        """Redeploy one tile from the recovery source (last checkpoint or the
        deterministic initial board); the new owner replays forward by
        pulling epoch-tagged halos (the ``onCellTermination`` path)."""
        # Supervision replay in flight: dump the ring so the artifact holds
        # the spans/events that led to this tile needing a restart.
        self.tracer.flight.dump("tile_redeploy", node="frontend")
        with self._lock:
            member = self._assign_tile_locked(tile, preferred=preferred, avoid=avoid)
            if member is None:
                return
            # Re-wire everyone first (NeighboursRefs re-send to the whole
            # neighborhood, BoardCreator.scala:149-151), then deploy.
            self._broadcast_owners_locked()
        self._send_deploy(member, [tile])

    # -- maintenance: ticks, auto-down, fault injection ----------------------

    def _maintenance_loop(self) -> None:
        while not self._stop.is_set() and not self.done.is_set():
            time.sleep(_MAINT_INTERVAL_S)
            now = time.monotonic()
            # periodic --metrics-file refresh (atomic; scrape-safe mid-run;
            # failure containment lives in the shared MetricsDumper — an
            # unwritable path must not kill the maintenance thread, which
            # ticks, evicts, and injects).
            if self._metrics_dumper is not None:
                self._metrics_dumper.maybe(now)
            # Advance the wire-chaos partition schedule even when no
            # traffic flows (blocked links poll on send; a fully-stalled
            # cluster still needs the heal clock to tick).
            if self.netchaos is not None:
                self.netchaos.poll(now)
            # Per-member control-plane staleness, refreshed every pass so
            # operators see heartbeat age climbing BEFORE auto-down fires
            # (also surfaced in /healthz as heartbeat_age_s).
            for m in self.membership.alive_members():
                self._m_hb_age.labels(member=m.name).set(
                    max(0.0, now - m.last_seen)
                )
            # Degraded-mode detection BEFORE auto-down: a partition that
            # strands a quorum of tiles must flip the cluster into waiting,
            # not evict every silent-but-alive member.
            self._check_degraded(now)
            # auto-down stale members (application.conf:23 analog) —
            # suppressed while degraded: silence during a partition is the
            # partition's fault, not the members'
            with self._lock:
                degraded = self.degraded
                drain_only = self.paused
            if not degraded:
                for m in self.membership.stale_members(now):
                    self._on_member_lost(m.name)
            # The elastic plane: expire/plan migrations, release drains.
            # A paused cluster still honors drains (a paused tile is not
            # stepping, so moving it is safe; a SIGTERM'd worker must be
            # able to leave gracefully mid-pause) but never reshapes for
            # load.
            self._rebalance_poll(now, drain_only=drain_only)
            # The serve plane's elastic pass (session shards) runs even
            # before/without start_simulation — a serve-only cluster
            # rebalances from its first worker.
            if self.serve_plane is not None and not degraded:
                self.serve_plane.poll(now, drain_only=drain_only)
                self._check_drains()
            # paced epoch announcements
            with self._lock:
                if (
                    self._started.is_set()
                    and not self.paused
                    and self.config.tick_s > 0
                    and self._next_tick is not None
                    and now >= self._next_tick
                    and self.target_epoch < self.config.max_epochs
                ):
                    if self._stop.is_set() or self.done.is_set():
                        # stop() is concurrently finishing the run's spans
                        # (under this lock): rotating now would mint an
                        # epoch span nobody ever finishes.
                        continue
                    self.target_epoch += 1
                    self._next_tick = now + self.config.tick_s
                    # One epoch span per announcement in paced mode: close
                    # the previous target's span, open the next under the
                    # run root, and ride its context on every TICK.
                    if self._epoch_span is not None:
                        self._epoch_span.finish()
                    self._epoch_span = self.tracer.start(
                        "epoch", parent=self._run_span, node="frontend",
                        target=self.target_epoch,
                    )
                    msg = attach_trace(
                        {"type": P.TICK, "target": self.target_epoch},
                        self._epoch_span,
                    )
                    for m in self.membership.alive_members():
                        self._safe_send(m, msg)
            # fault injection (BoardCreator.scala:97-102 analog)
            if (
                self.injector is not None
                and self._started.is_set()
                and self.injector.should_crash(now)
            ):
                self._inject_crash()

    def _check_degraded(self, now: float) -> None:
        """Enter/leave degraded mode.

        *Stranded* means a tile has pushed no ring/progress for
        ``stuck_timeout_s``; when at least half the board is stranded the
        stall is systemic.  Degraded mode makes the recovery source durable
        (checkpoint what we have), logs ``cluster.degraded``, and suspends
        heartbeat auto-down — silence during a partition is the partition's
        fault, and evicting live members would orphan state that will
        resume on heal.  Stuck-neighbor redeploys stay available unless the
        injected chaos plane announces an active partition (see
        ``_on_gather_failed``): a wedged-but-alive worker's tiles must
        still move to healthy members.  When rings flow again the mode
        lifts and the cluster resumes cleanly from live state.
        """
        with self._lock:
            if not self._started.is_set() or self.paused or self.layout is None:
                return
            tiles = self.layout.tile_ids
            stranded = sum(
                1
                for t in tiles
                if not self._quiescent_fresh_locked(t, now)
                and now - self._last_ring_time.get(t, now)
                > self.config.stuck_timeout_s
            )
            quorum = 2 * stranded >= len(tiles)
            if quorum and not self.degraded:
                self.degraded = True
                self._m_degraded.set(1)
                self._m_degraded_entries.inc()
                self._degraded_span = self.tracer.start(
                    "cluster.degraded", parent=self._run_span, node="frontend",
                    stranded=stranded, tiles=len(tiles),
                    epoch=self._last_ckpt[0],
                )
                self.tracer.flight.dump("degraded", node="frontend")
                self.events.emit(
                    "cluster_degraded",
                    stranded=stranded,
                    tiles=len(tiles),
                    epoch=self._last_ckpt[0],
                )
                # Checkpoint what we have: the last consistent per-tile set
                # becomes durable NOW — if the partition outlives the
                # operator's patience, a restarted frontend resumes from it.
                if self.store is not None:
                    epoch, payloads = self._last_ckpt
                    for t, payload in payloads.items():
                        self._io_queue.put(("tile", (epoch, t, payload)))
                    self._io_queue.put(
                        (
                            "finalize",
                            (
                                epoch,
                                self.rule.rulestring(),
                                self.layout.grid,
                                self.config.shape,
                                self._digest_meta_locked(epoch),
                            ),
                        )
                    )
            elif self.degraded and not quorum:
                self.degraded = False
                self._m_degraded.set(0)
                if self._degraded_span is not None:
                    self._degraded_span.set(healed=True).finish()
                    self._degraded_span = None
                self.events.emit("cluster_degraded_healed")

    def _inject_crash(self) -> None:
        members = [m for m in self.membership.alive_members() if m.tiles]
        if not members:
            return
        rng = self.injector.rng
        victim = rng.choice(members)
        mode = self.config.fault_injection.mode
        if mode == "node":
            self.crash_events.append({"mode": "node", "victim": victim.name})
            self.events.emit("crash_injected", mode="node", victim=victim.name)
            # Trace context on the kill order: the victim's backend.crash
            # span (and its flight dump) link to the epoch they interrupt.
            self._safe_send(
                victim, attach_trace({"type": P.CRASH}, self._epoch_span)
            )
        else:
            tile = rng.choice(victim.tiles)
            self.crash_events.append(
                {"mode": "tile", "victim": victim.name, "tile": tile}
            )
            self.events.emit(
                "crash_injected",
                mode="tile",
                victim=victim.name,
                tile=list(tile),
            )
            self._safe_send(
                victim,
                attach_trace(
                    {"type": P.CRASH_TILE, "tile": list(tile)}, self._epoch_span
                ),
            )


def run_frontend(config: SimulationConfig, *, min_backends: int = 1) -> int:
    """CLI entry: serve the cluster until the simulation completes."""
    fe = Frontend(config, min_backends=min_backends)
    fe.start()
    print(f"frontend listening on {config.host}:{fe.port}", flush=True)
    try:
        if not fe.wait_for_backends():
            print(
                f"error: only {len(fe.membership.alive_members())} of "
                f"{min_backends} backends joined within "
                f"{config.wait_for_backends_s}s",
                flush=True,
            )
            fe.stop()
            return 1
        # SIGUSR1 toggles pause/resume — the reference's PauseSimulation/
        # ResumeSimulation messages existed but nothing ever sent them
        # (BoardCreator.scala:109-112, dead code); here an operator can.  The
        # handler runs on the main thread (blocked in done.wait(), holding no
        # locks), so calling pause()/resume() directly is safe.
        import signal as _signal

        def _toggle_pause(signum, frame):
            if fe.paused:
                print("resuming (SIGUSR1)", flush=True)
                fe.resume()
            else:
                print("pausing (SIGUSR1)", flush=True)
                fe.pause()

        try:
            _signal.signal(_signal.SIGUSR1, _toggle_pause)
        except (ValueError, AttributeError):  # non-main thread / no SIGUSR1
            pass

        try:
            # A worker may die between quorum and deployment.
            fe.start_simulation()
        except RuntimeError as e:
            print(f"error: {e}", flush=True)
            fe.stop()
            return 1
        fe.done.wait()
    except KeyboardInterrupt:
        # Graceful operator stop (^C / SIGTERM via the CLI mapping), in ANY
        # post-start window — quorum wait, tile deployment, or the serve
        # loop: send SHUTDOWN to every worker so they leave rc=0, drain
        # queued checkpoint writes, close the store.  Durable state = the
        # cadence checkpoints; a restarted frontend resumes from them
        # (tests/test_cluster.py frontend-restart-resumes).  The drain is
        # masked against a second signal — aborting it half-way would drop
        # queued checkpoint writes while still exiting 130.
        from akka_game_of_life_tpu.runtime.signals import mask_interrupts

        print("interrupted; shutting the cluster down", flush=True)
        with mask_interrupts():
            fe.stop()
        return 130
    fe.stop()
    if fe.error:
        print(f"error: {fe.error}", flush=True)
        return 1
    print(f"simulation complete at epoch {config.max_epochs}", flush=True)
    return 0
