"""Tracing & profiling — SURVEY.md §5's tracing slot.

The reference has no tracing at all (its only artifact is an unused sbt
Activator shim, ``project/inspect.sbt:1-3``); the TPU-native replacement is
the XLA profiler: ``trace(dir)`` captures a device+host timeline viewable in
TensorBoard/Perfetto (XLA op breakdown, HBM traffic, host callbacks), and
:func:`annotate_epochs` marks each host-loop chunk so step boundaries show up
on the timeline.

Usage:

    from akka_game_of_life_tpu.runtime import profiling
    with profiling.trace("/tmp/gol-trace"):
        sim.advance(512)

or ``python -m akka_game_of_life_tpu run ... --trace-dir /tmp/gol-trace``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``trace_dir`` (no-op when None)."""
    if not trace_dir:
        yield
        return
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_epochs(name: str, epoch: int):
    """Mark one host-loop chunk on the profiler timeline (shows as a step
    with ``step_num=epoch`` in the trace viewer)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=epoch)


class TimedSpan:
    """The measurement a :func:`timed` block yields: ``seconds`` is 0.0
    while the block runs and the measured duration once it exits, so
    callers can record or aggregate what used to be print-only."""

    __slots__ = ("label", "seconds")

    def __init__(self, label: str) -> None:
        self.label = label
        self.seconds = 0.0

    @property
    def ms(self) -> float:
        return self.seconds * 1e3


@contextlib.contextmanager
def timed(
    label: str, out=None, registry=None, span: Optional[str] = None
) -> Iterator[TimedSpan]:
    """Host-side wall-clock span, printed on exit — the quick-look
    complement to the full trace.

    Yields a :class:`TimedSpan` whose ``seconds`` carries the measured
    duration after the block exits.  With ``registry`` (a
    :class:`~akka_game_of_life_tpu.obs.MetricsRegistry`), the duration is
    also observed into the ``gol_span_seconds`` histogram under the
    ``span`` label (default: ``label`` up to the first ``@`` — epoch-stamped
    labels like ``checkpoint@512`` must not mint one series per epoch)."""
    rec = TimedSpan(label)
    t0 = time.perf_counter()
    try:
        yield rec
    finally:
        rec.seconds = time.perf_counter() - t0
        msg = f"[profile] {label}: {rec.ms:.2f} ms"
        if out is None:
            print(msg, flush=True)
        else:
            print(msg, file=out, flush=True)
        if registry is not None:
            registry.histogram(
                "gol_span_seconds", labelnames=("span",)
            ).labels(span=span or label.split("@", 1)[0]).observe(rec.seconds)
        # Tracing bridge: when a trace span is active on this thread, the
        # timed block becomes its child (same @-stripped naming rule as the
        # histogram) — every existing timed() site lights up on the epoch
        # timeline for free.  No active span = no-op.
        from akka_game_of_life_tpu.obs import tracing

        tracing.record_timed(label, rec.seconds, span=span)


def device_memory_stats() -> dict:
    """Per-device memory stats where the backend exposes them (TPU does;
    CPU returns empty)."""
    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (AttributeError, jax.errors.JaxRuntimeError):
            stats = None
        if stats:
            out[str(d)] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
    return out


class ProfilerCapture:
    """On-demand, rate-limited jax.profiler capture — ``POST /profile``'s
    engine on every role.

    One capture at a time per process (a second request while one runs
    gets ``status: 409``), at most one per ``min_interval_s`` (``status:
    429`` with ``retry_after_s``), each clamped to ``max_seconds`` — an
    unauthenticated scraper poking the obs port must not be able to turn
    the profiler into a DoS.  Artifacts land under
    ``<artifacts_dir>/profile-<node>-<stamp>/`` in the standard
    TensorBoard/Perfetto layout :func:`trace` produces.

    ``start``/``stop``/``sleep``/``clock`` are injectable so tests drive
    captures without a real profiler or wall time.
    """

    def __init__(
        self,
        artifacts_dir: str = "artifacts",
        *,
        node: Optional[str] = None,
        max_seconds: float = 30.0,
        min_interval_s: float = 60.0,
        default_seconds: float = 3.0,
        clock=time.monotonic,
        sleep=time.sleep,
        start=None,
        stop=None,
    ) -> None:
        import threading

        self.artifacts_dir = artifacts_dir
        self.node = node or "local"
        self.max_seconds = float(max_seconds)
        self.min_interval_s = float(min_interval_s)
        self.default_seconds = float(default_seconds)
        self._clock = clock
        self._sleep = sleep
        self._start = start if start is not None else jax.profiler.start_trace
        self._stop = stop if stop is not None else jax.profiler.stop_trace
        self._lock = threading.Lock()
        self._running = False  # graftlint: guarded-by _lock
        self._last: Optional[float] = None  # graftlint: guarded-by _lock
        self._seq = 0  # graftlint: guarded-by _lock

    def capture(self, seconds: Optional[float] = None) -> dict:
        """Run one capture window, blocking for its duration.  Returns a
        JSON-ready result: ``{"ok": True, "artifact", "seconds"}`` or
        ``{"ok": False, "error", "status"}`` (409 busy, 429 rate-limited,
        500 profiler failure)."""
        want = self.default_seconds if seconds is None else float(seconds)
        want = min(max(want, 0.1), self.max_seconds)
        with self._lock:
            if self._running:
                return {
                    "ok": False,
                    "status": 409,
                    "error": "a profiler capture is already running",
                }
            now = self._clock()
            if self._last is not None and now - self._last < self.min_interval_s:
                return {
                    "ok": False,
                    "status": 429,
                    "error": "profiler capture rate-limited",
                    "retry_after_s": round(
                        self.min_interval_s - (now - self._last), 3
                    ),
                }
            self._running = True
            self._last = now
            self._seq += 1
            seq = self._seq
        import os

        path = os.path.join(
            self.artifacts_dir, f"profile-{self.node}-{seq:04d}"
        )
        try:
            os.makedirs(path, exist_ok=True)
            self._start(path)
            try:
                self._sleep(want)
            finally:
                self._stop()
        except Exception as e:  # noqa: BLE001 — report, never kill the route
            return {"ok": False, "status": 500, "error": repr(e)}
        finally:
            with self._lock:
                self._running = False
        from akka_game_of_life_tpu.obs.metrics import get_registry

        get_registry().counter(
            "gol_profile_captures_total",
            "On-demand jax.profiler captures taken (POST /profile)",
        ).inc()
        return {
            "ok": True,
            "node": self.node,
            "artifact": path,
            "seconds": want,
            "devices": device_memory_stats(),
        }
