"""Tracing & profiling — SURVEY.md §5's tracing slot.

The reference has no tracing at all (its only artifact is an unused sbt
Activator shim, ``project/inspect.sbt:1-3``); the TPU-native replacement is
the XLA profiler: ``trace(dir)`` captures a device+host timeline viewable in
TensorBoard/Perfetto (XLA op breakdown, HBM traffic, host callbacks), and
:func:`annotate_epochs` marks each host-loop chunk so step boundaries show up
on the timeline.

Usage:

    from akka_game_of_life_tpu.runtime import profiling
    with profiling.trace("/tmp/gol-trace"):
        sim.advance(512)

or ``python -m akka_game_of_life_tpu run ... --trace-dir /tmp/gol-trace``.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a jax.profiler trace into ``trace_dir`` (no-op when None)."""
    if not trace_dir:
        yield
        return
    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate_epochs(name: str, epoch: int):
    """Mark one host-loop chunk on the profiler timeline (shows as a step
    with ``step_num=epoch`` in the trace viewer)."""
    return jax.profiler.StepTraceAnnotation(name, step_num=epoch)


class TimedSpan:
    """The measurement a :func:`timed` block yields: ``seconds`` is 0.0
    while the block runs and the measured duration once it exits, so
    callers can record or aggregate what used to be print-only."""

    __slots__ = ("label", "seconds")

    def __init__(self, label: str) -> None:
        self.label = label
        self.seconds = 0.0

    @property
    def ms(self) -> float:
        return self.seconds * 1e3


@contextlib.contextmanager
def timed(
    label: str, out=None, registry=None, span: Optional[str] = None
) -> Iterator[TimedSpan]:
    """Host-side wall-clock span, printed on exit — the quick-look
    complement to the full trace.

    Yields a :class:`TimedSpan` whose ``seconds`` carries the measured
    duration after the block exits.  With ``registry`` (a
    :class:`~akka_game_of_life_tpu.obs.MetricsRegistry`), the duration is
    also observed into the ``gol_span_seconds`` histogram under the
    ``span`` label (default: ``label`` up to the first ``@`` — epoch-stamped
    labels like ``checkpoint@512`` must not mint one series per epoch)."""
    rec = TimedSpan(label)
    t0 = time.perf_counter()
    try:
        yield rec
    finally:
        rec.seconds = time.perf_counter() - t0
        msg = f"[profile] {label}: {rec.ms:.2f} ms"
        if out is None:
            print(msg, flush=True)
        else:
            print(msg, file=out, flush=True)
        if registry is not None:
            registry.histogram(
                "gol_span_seconds", labelnames=("span",)
            ).labels(span=span or label.split("@", 1)[0]).observe(rec.seconds)
        # Tracing bridge: when a trace span is active on this thread, the
        # timed block becomes its child (same @-stripped naming rule as the
        # histogram) — every existing timed() site lights up on the epoch
        # timeline for free.  No active span = no-op.
        from akka_game_of_life_tpu.obs import tracing

        tracing.record_timed(label, rec.seconds, span=span)


def device_memory_stats() -> dict:
    """Per-device memory stats where the backend exposes them (TPU does;
    CPU returns empty)."""
    out = {}
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except (AttributeError, jax.errors.JaxRuntimeError):
            stats = None
        if stats:
            out[str(d)] = {
                "bytes_in_use": stats.get("bytes_in_use"),
                "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                "bytes_limit": stats.get("bytes_limit"),
            }
    return out
