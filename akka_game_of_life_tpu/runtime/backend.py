"""The backend worker — the ``RunBackend`` role, upgraded from container to
shard engine.

The reference's backend is deliberately empty: it starts an ActorSystem,
joins the cluster, and hosts whatever cells the frontend deploys onto it
(``Run.scala:56-65``).  This worker keeps that shape — it owns nothing until
the frontend DEPLOYs tiles — but the deployed unit is a whole grid tile
advanced by a stencil engine:

- ``engine="numpy"``: host stepping, the portable/parity path;
- ``engine="jax"``: jitted stepping on the worker's local accelerator (the
  TPU path; within a multi-device worker the tile itself is mesh-sharded by
  :mod:`akka_game_of_life_tpu.parallel` — ICI inside, control plane outside);
- ``engine="swar"``: C++ 64-cells-per-uint64 SWAR chunks
  (``native/swar_kernel.cpp``) — host machine code for every radius-1
  family: binary totalistic (``swar_chunk``), wireworld (2-bit-plane
  ``swar_wire_chunk``), and Generations (m-plane ripple-carry
  ``swar_gen_chunk``); only radius-R LtL falls back to the numpy chunk;
- ``engine="actor"`` / ``"actor-native"``: the per-cell actor engine
  (:mod:`akka_game_of_life_tpu.runtime.actor_engine` and its C++ twin) —
  the reference's own architecture, swappable at role config (BASELINE
  config 1).

**The data plane is peer-to-peer.**  Workers serve each other's boundary
reads directly, exactly as the reference's gatherers ask neighbor cells
directly (``NextStateCellGathererActor.scala:32-36``) — the frontend only
brokers addresses and ownership (OWNERS), never relays ring bytes
(VERDICT.md weak #4: the round-1 star topology through the coordinator).
Each worker runs a peer listener plus a local epoch-tagged
:class:`BoundaryStore`; per-epoch cycle per tile:

  pull halo(E) from the LOCAL store (queued until all 8 neighbor rings at E
  are present) → step to E+1 → push RING(E+1) locally and PEER_RING it to
  each distinct owner of the tile's 8 neighbors → PROGRESS ping to the
  frontend (control only) → pull halo(E+1)...

A stale pull re-asks only the owners of the *missing* rings via PEER_PULL
(the gatherer's 1 s Retry, ``NextStateCellGathererActor.scala:28``) and
escalates to the frontend with GATHER_FAILED after ``max_pull_retries``.
Tiles lag and catch up independently — no global barrier, matching the
reference's history-buffered asynchrony (``CellActor.scala:41-47``)."""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from collections import deque
from typing import Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.obs import get_registry
from akka_game_of_life_tpu.obs.tracing import get_tracer
from akka_game_of_life_tpu.ops.npkernel import step_padded_np
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.boundary import (
    BoundaryStore,
    Halo,
    halos_equal,
)
from akka_game_of_life_tpu.runtime.netchaos import (
    ChaosChannel,
    CircuitBreaker,
    NetworkChaos,
    wrap_channel,
)
from akka_game_of_life_tpu.runtime.tiles import Ring, TileId, TileLayout
from akka_game_of_life_tpu.runtime.wire import (
    Channel,
    decode_ring,
    encode_ring,
    extract_trace,
    pack_tile,
    ring_entry_nbytes,
    split_ring_batches,
    unpack_tile,
)


class _Tile:
    def __init__(self, arr: np.ndarray, epoch: int, retry_s: float = 1.0) -> None:
        self.arr = arr
        self.epoch = epoch
        self.awaiting_since: Optional[float] = None  # the waitingForNewState latch
        self.retries = 0
        # Adaptive re-pull pacing (decorrelated-jitter backoff): the delay
        # the LAST retry chose (feeds the next draw) and the deadline the
        # next retry fires at.  Both reset when a pull succeeds.
        self.retry_delay = retry_s
        self.next_retry_at = 0.0
        # Live-migration freeze (MIGRATE_PREPARE): while monotonic time is
        # before this, the tile starts no new chunk — its state is the
        # canonical copy a migration is shipping.  0 = not frozen.  The
        # deadline is the self-healing rollback: if the frontend's COMMIT
        # (an OWNERS rewiring that drops the tile) or MIGRATE_ABORT never
        # arrives, the retry loop unfreezes and resumes at expiry.
        self.frozen_until = 0.0
        # Quiescence tier (sparse_cluster): the last up-to-two chunk inputs
        # as (state, halo, chunk_len) — references, never copies (compute
        # always allocates a new array, so old ones stay valid).  A chunk
        # whose (state, halo, len) matches inputs[0] is a fixed point
        # (period 1); matching inputs[1] is period 2 — either way its
        # output is already known and the compute is skipped.
        self.inputs: Deque[Tuple[np.ndarray, object, int]] = deque(maxlen=2)
        # The last two published (Ring, epoch) pairs, for the O(1)-byte
        # "same-ring" markers a skipped chunk publishes instead of payload.
        self.last_ring: Optional[Tuple[object, int]] = None
        self.prev_ring: Optional[Tuple[object, int]] = None
        self.q_period = 0  # 0 = active; 1/2 = quiescent at that period
        self.q_skipped = 0  # chunks skipped since the last PROGRESS ping
        # Adaptive backoff for the O(tile) quiescence probes: an interior-
        # active tile behind a static halo doubles its wait (capped) after
        # each failed state compare, so the gate's detection cost amortizes
        # toward zero on tiles that refuse to quiesce.
        self.q_probe_wait = 0
        self.q_probe_backoff = 0


# VMEM row block for the cluster's Mosaic chunk sweeps (the measured-best
# block — BASELINE.md); slabs are junk-row-padded up to a multiple of it.
_PALLAS_CHUNK_BLOCK = 128


def _jax_engine(
    rule: Rule, pallas: Optional[str] = None
) -> Callable[[np.ndarray, int, int], np.ndarray]:
    """Jitted tile stepping on the worker's local accelerator(s).

    Takes a width-k halo-padded (h+2k, w+2k) slab and advances the (h, w)
    interior by ``steps`` (<= k) generations in ONE device round-trip: a
    ``lax.scan`` of the *toroidal* step at constant shape — the wraps only
    ever corrupt the outermost halo cells, which are cut edges whose garbage
    front moves one cell per step, so the interior slice is exact (the same
    argument as ``parallel/packed_halo2d.py``).  This is the cluster's
    communication-avoiding engine: one exchange, k on-device epochs, zero
    per-epoch host round-trips inside the chunk.  Binary multi-step chunks
    scan bit-packed (32 cells/lane); multi-state plane rules (Generations,
    wireworld) scan as bit planes (``ops/bitpack_gen``); everything else
    (radius-R LtL, single-step chunks) scans dense uint8.

    On a single real-TPU device, binary multi-step chunks step through the
    Mosaic temporal-blocking sweep (``ops/pallas_stencil.py``) instead of
    the XLA packed scan — the slab is junk-row-padded up to a whole number
    of VMEM row blocks (junk sits between the south halo and the wrapped
    north halo, both cut edges, so with steps <= halo it never reaches the
    interior) — with a one-time demotion to the XLA scan if Mosaic fails.
    ``pallas`` pins the choice: None = auto, "off" disables,
    "interpret" forces the sweep in interpret mode (CPU-testable).

    With more than one local device the slab is row-sharded over a 1-D local
    mesh and the scan jitted with sharding constraints — GSPMD inserts the
    interior halo exchanges itself, so a worker on a multi-chip host spreads
    its tile across its chips (ICI inside the worker, the cluster control
    plane outside).  Single device degenerates to a plain jit."""
    import jax
    import jax.numpy as jnp

    from akka_game_of_life_tpu.ops import bitpack
    from akka_game_of_life_tpu.ops.stencil import step as stencil_step

    if pallas not in (None, "auto", "off", "interpret"):
        raise ValueError(
            f"unknown pallas mode {pallas!r}; use auto, off, or interpret"
        )
    devices = jax.local_devices()
    if pallas == "interpret":
        # Testing mode: force the single-device branch so the sweep really
        # runs (the conftest's virtual 8-device host would otherwise route
        # to the multi-device scan and silently skip the path under test).
        devices = devices[:1]
    compiled: Dict[tuple, Callable] = {}  # (steps, col_pad, row_pad) → chunk fn
    use_pallas = (
        pallas != "off"
        and rule.is_binary
        and len(devices) == 1
        and (pallas == "interpret" or jax.default_backend() == "tpu")
    )
    # Binary rules step BIT-PACKED on device (the certified-fast SWAR path —
    # VERDICT.md round-2 next #1: the cluster jax engine must run the packed
    # kernel, not only bench.py): the uint8 slab packs to uint32 words on
    # device, the whole chunk scans packed, and unpacks before the interior
    # slice.  Multi-state plane rules (Generations ≤ 256 states, wireworld)
    # step as bit planes the same way (ops/bitpack_gen, m = ⌈log₂S⌉ planes).
    # Single-step chunks (exchange_width=1) keep the dense scan either way:
    # pack+unpack costs ~2.25 B/cell of HBM traffic around ~0.25·m B/cell
    # packed steps vs ~2 B/cell dense, so packing only wins once a chunk
    # amortizes it over >= 2 steps.
    from akka_game_of_life_tpu.ops import bitpack_gen

    plane_capable = (
        not rule.is_binary
        and (rule.is_totalistic or rule.kind == "wireworld")
        and rule.states <= 256
    )

    def _use_packed(steps: int) -> bool:
        return rule.is_binary and steps >= 2

    def _use_planes(steps: int) -> bool:
        return plane_capable and steps >= 2

    def _chunk_fn(steps: int, col_pad: int, row_pad: int = 0):
        packed = _use_packed(steps)
        planes = _use_planes(steps)
        mosaic_steps = None
        if packed and use_pallas:
            from akka_game_of_life_tpu.ops import pallas_stencil

            # The lru-cached Mosaic multi-step (sweep-count bookkeeping and
            # validation live there); jit nesting inlines it into the chunk.
            mosaic_steps = pallas_stencil.packed_multi_step_fn(
                rule,
                steps,
                block_rows=_PALLAS_CHUNK_BLOCK,
                interpret=pallas == "interpret",
            )

        def chunk(padded):
            if packed or planes:
                if col_pad:
                    # Junk columns up to a 32-multiple.  They sit between the
                    # east halo and the (toroidally wrapped) west halo — both
                    # cut edges whose garbage front moves one cell per step —
                    # so with steps <= halo they never reach the interior
                    # slice, exactly like the junk rows below.
                    padded = jnp.pad(padded, ((0, 0), (0, col_pad)))
                if mosaic_steps is not None and row_pad:
                    # Junk rows up to a VMEM-block multiple for the Mosaic
                    # sweep (same cut-edge argument, row-wise).
                    padded = jnp.pad(padded, ((0, row_pad), (0, 0)))
                if planes:
                    state = bitpack_gen.pack_gen(padded, rule.states)
                    step_one = lambda s: bitpack_gen.step_gen(s, rule)
                else:
                    state = bitpack.pack(padded)
                    step_one = lambda s: bitpack.step_packed(s, rule)
            else:
                state = padded
                step_one = lambda s: stencil_step(s, rule)
            if mosaic_steps is not None:
                out = mosaic_steps(state)
            else:
                out, _ = jax.lax.scan(
                    lambda s, _: (step_one(s), None), state, None, length=steps
                )
            if planes:
                out = bitpack_gen.unpack_gen(out)
                if col_pad:
                    out = out[:, :-col_pad]
            elif packed:
                out = bitpack.unpack(out)
                if mosaic_steps is not None and row_pad:
                    out = out[:-row_pad]
                if col_pad:
                    out = out[:, :-col_pad]
            return out

        return chunk

    def _col_pad(width: int, steps: int) -> int:
        if _use_packed(steps) or _use_planes(steps):
            return (-width) % bitpack.LANE_BITS
        return 0

    if len(devices) == 1:

        def run(padded: np.ndarray, steps: int, halo: int) -> np.ndarray:
            nonlocal use_pallas
            assert steps <= halo, (steps, halo)
            mosaic = _use_packed(steps) and use_pallas
            row_pad = (
                (-padded.shape[0]) % _PALLAS_CHUNK_BLOCK if mosaic else 0
            )
            key = (steps, _col_pad(padded.shape[1], steps), row_pad)
            fn = compiled.get(key)
            if fn is None:
                from akka_game_of_life_tpu.obs.programs import (
                    registered_jit,
                    stencil_cost,
                )

                fn = compiled[key] = registered_jit(
                    "worker_chunk", ("single", rule.name, key),
                    jax.jit(_chunk_fn(*key)),
                    cost=lambda p, _s=steps: stencil_cost(
                        p.shape[-2], p.shape[-1], _s
                    ),
                )
            try:
                out = fn(jnp.asarray(padded))
                return np.asarray(out[halo:-halo, halo:-halo])
            except Exception as e:  # noqa: BLE001 — Mosaic failure demotes
                if not mosaic:
                    # This chunk never contained Pallas code; nothing to
                    # demote — the error is the caller's to see.
                    raise
                import sys

                print(
                    f"cluster jax engine: Mosaic chunk failed "
                    f"({type(e).__name__}: {e}); demoting this worker to "
                    f"the XLA packed scan",
                    file=sys.stderr,
                    flush=True,
                )
                use_pallas = False
                compiled.clear()
                return run(padded, steps, halo)

        return run

    from jax.sharding import NamedSharding, PartitionSpec

    n = len(devices)
    # Auto axis type: GSPMD propagates shardings through the stencil's
    # slices/rolls itself (explicit mode refuses non-divisible slicing).
    mesh = jax.make_mesh(
        (n,), ("rows",), devices=devices, axis_types=(jax.sharding.AxisType.Auto,)
    )
    rows = NamedSharding(mesh, PartitionSpec("rows", None))

    def run(padded: np.ndarray, steps: int, halo: int) -> np.ndarray:
        assert steps <= halo, (steps, halo)
        h_out = padded.shape[0] - 2 * halo
        pad = (-padded.shape[0]) % n
        if pad:
            # Row-pad up to a mesh multiple.  The junk rows sit below the
            # south halo; the toroidal wrap feeds their garbage into the
            # outermost halo rows (already cut edges), and both fronts move
            # one row per step — with steps <= halo the interior slice below
            # is never reached.
            padded = np.pad(padded, ((0, pad), (0, 0)))
        key = (steps, _col_pad(padded.shape[1], steps))
        fn = compiled.get(key)
        if fn is None:
            from akka_game_of_life_tpu.obs.programs import (
                registered_jit,
                stencil_cost,
            )

            fn = compiled[key] = registered_jit(
                "worker_chunk", ("meshed", rule.name, n, key),
                jax.jit(_chunk_fn(*key), in_shardings=rows),
                cost=lambda p, _s=steps: stencil_cost(
                    p.shape[-2], p.shape[-1], _s
                ),
            )
        out = fn(jax.device_put(padded, rows))
        return np.asarray(out)[halo : halo + h_out, halo:-halo]

    return run


def _np_chunk(padded: np.ndarray, steps: int, halo: int, rule: Rule) -> np.ndarray:
    """Host-engine chunk: ``steps`` (<= halo) epochs on a width-``halo``
    padded slab; each step peels one boundary layer, then the exact (h, w)
    interior is sliced out."""
    assert steps <= halo, (steps, halo)
    h, w = padded.shape[0] - 2 * halo, padded.shape[1] - 2 * halo
    out = padded
    for _ in range(steps):
        out = step_padded_np(out, rule)
    m = halo - steps  # remaining margin after `steps` peels
    return out[m : m + h, m : m + w]


# Batch linger: a pending outbound ring batch that has not been sealed by
# its expected contributors (tiles redeployed away, catch-up replay at mixed
# epochs) flushes after this long.  A backstop, not the steady-state path —
# in steady state the LAST contributing tile's publish seals the batch with
# zero added latency — and even a wedged batch self-heals through the
# receiver's PEER_PULL re-asks (our rings are always in our local store).
_RING_LINGER_S = 0.02


class _PeerSender:
    """One peer's async outbound lane: a bounded queue drained by a writer
    thread, so ``_publish_ring`` never blocks the step loop on a slow
    socket, a connect timeout, or a chaos-blocked link.

    Ring entries coalesce: entries for one epoch accumulate into a pending
    batch that *seals* (becomes one PEER_RING_BATCH frame) when every local
    tile known to border this peer has contributed, when an entry for a
    different epoch arrives, or after ``_RING_LINGER_S`` — whichever comes
    first.  Control messages (PEER_PULL asks, unbatched rings) bypass the
    pending batch but share the queue, the depth bound, and the writer.

    The writer composes with the rest of the hardened stack unchanged: the
    per-peer circuit breaker gates each drain, the channel may be a
    ``ChaosChannel`` (partition blocks raise here, on the writer — never on
    a compute thread), and a send deadline surfaces as the same ``OSError``
    drop-and-redial path."""

    # Lock discipline (tools/graftlint): every batch/queue field belongs to
    # the condition; the writer and every producer agree on one monitor.
    _GRAFTLINT_GUARDED = {
        "_items": "_cond",
        "_pending": "_cond",
        "_pending_tiles": "_cond",
        "_expect": "_cond",
        "_pending_epoch": "_cond",
        "_pending_since": "_cond",
        "_depth": "_cond",
        "_closed": "_cond",
    }

    def __init__(self, worker: "BackendWorker", owner: str) -> None:
        self.worker = worker
        self.owner = owner
        self._cond = threading.Condition()
        # ("batch", [entry, ...]) | ("msg", dict) — sealed, ready to send.
        self._items: Deque[Tuple[str, object]] = deque()
        self._pending: List[dict] = []
        self._pending_tiles: set = set()
        self._expect: set = set()
        self._pending_epoch: Optional[int] = None
        self._pending_since = 0.0
        self._depth = 0  # running entry count (pending + items), O(1) trim
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"peer-send-{owner}"
        )
        self._thread.start()

    # -- producer side (compute/serve threads; never touches the socket) -----

    def enqueue_msg(self, msg: dict) -> None:
        with self._cond:
            if self._closed:
                return
            self._items.append(("msg", msg))
            self._depth += 1
            self._trim_locked()
            self._cond.notify()

    def enqueue_ring(self, entry: dict, expect) -> None:
        """Add one encoded ring entry to the peer's building batch.
        ``expect`` is the set of local tiles currently bordering this peer —
        the seal condition that gives full batches with zero added latency
        in steady state."""
        with self._cond:
            if self._closed:
                return
            epoch = entry["epoch"]
            if self._pending and epoch != self._pending_epoch:
                self._seal_locked()
            if not self._pending:
                self._pending_epoch = epoch
                self._expect = set(expect)
                self._pending_since = time.monotonic()
            self._pending.append(entry)
            self._pending_tiles.add(tuple(entry["tile"]))
            self._depth += 1
            if self._pending_tiles >= self._expect:
                self._seal_locked()
            self._trim_locked()
            self._cond.notify()

    def _seal_locked(self) -> None:
        if self._pending:
            self._items.append(("batch", self._pending))
            self._pending = []
            self._pending_tiles = set()
            self._pending_epoch = None

    def _trim_locked(self) -> None:
        """Bounded queue, drop-OLDEST: a wedged peer must not grow worker
        memory, and anything dropped is recoverable — the receiver's retry
        loop re-asks via PEER_PULL and our rings stay in the local store.
        ``_depth`` is a running counter so the hot enqueue path stays O(1)
        even when the queue is full (the wedged-peer case is exactly when
        an O(queue) rescan per publish would hurt most)."""
        w = self.worker
        while self._depth > w.ring_queue_depth and self._items:
            kind, payload = self._items.popleft()
            dropped = len(payload) if kind == "batch" else 1
            self._depth -= dropped
            w._m_queue_drops.inc(dropped)
        w._m_queue_depth.labels(peer=self.owner).set(self._depth)

    # -- writer side ----------------------------------------------------------

    def close(self) -> None:
        with self._cond:
            self._closed = True
            # Gauge hygiene (the breaker-reset discipline): a departed
            # peer must not leave a stale non-zero queue-depth series.
            # Under the condition lock, and mirrored by the writer's own
            # exit path — whichever runs last leaves the series at 0.
            self.worker._m_queue_depth.labels(peer=self.owner).set(0)
            self._cond.notify()

    def _run(self) -> None:
        w = self.worker
        while True:
            with self._cond:
                while not self._items:
                    if self._closed or w._stop.is_set():
                        w._m_queue_depth.labels(peer=self.owner).set(0)
                        return
                    timeout = 0.2  # poll _stop even if nobody notifies
                    if self._pending:
                        timeout = (
                            self._pending_since + _RING_LINGER_S
                            - time.monotonic()
                        )
                        if timeout <= 0:
                            self._seal_locked()
                            break
                        timeout = min(timeout, 0.2)
                    self._cond.wait(timeout)
                items = list(self._items)
                self._items.clear()
                self._depth = len(self._pending)
                w._m_queue_depth.labels(peer=self.owner).set(self._depth)
            self._send(items)

    @staticmethod
    def _coalesce_pulls(
        items: List[Tuple[str, object]]
    ) -> List[Tuple[str, object]]:
        """Merge queued PEER_PULL asks for the same epoch into one frame —
        the ask-side analog of ring batching.  When several local tiles go
        stale on the same peer in the same chunk (the common case: they all
        wait on one in-flight batch), the drain sends O(epochs) ask frames
        instead of O(tiles)."""
        merged: List[Tuple[str, object]] = []
        pulls: Dict[int, dict] = {}
        for kind, payload in items:
            if (
                kind == "msg"
                and isinstance(payload, dict)
                and payload.get("type") == P.PEER_PULL
            ):
                tiles = [
                    list(t)
                    for t in (payload.get("tiles") or [payload["tile"]])
                ]
                epoch = int(payload["epoch"])
                m = pulls.get(epoch)
                if m is None:
                    m = {"type": P.PEER_PULL, "tiles": tiles, "epoch": epoch}
                    pulls[epoch] = m
                    merged.append(("msg", m))
                else:
                    seen = {tuple(t) for t in m["tiles"]}
                    m["tiles"].extend(
                        t for t in tiles if tuple(t) not in seen
                    )
                continue
            merged.append((kind, payload))
        return merged

    def _send(self, items: List[Tuple[str, object]]) -> None:
        w = self.worker
        items = self._coalesce_pulls(items)
        # Breaker first: a dead/partitioned peer costs one state read per
        # drain, not a connect timeout — the retry loop (backoff) and the
        # breaker's own half-open probes are the only traffic re-testing it.
        if not w.breaker.allow(self.owner):
            return
        ch = w._peer_channel(self.owner)
        if ch is None:
            w.breaker.failure(self.owner)
            return
        try:
            for kind, payload in items:
                if kind == "batch":
                    for frame in split_ring_batches(payload):
                        with w.tracer.span(
                            "halo.batch_send", parent=w._trace_ctx,
                            node=w.name or "backend", peer=self.owner,
                            rings=len(frame),
                        ):
                            ch.send(
                                {"type": P.PEER_RING_BATCH, "rings": frame}
                            )
                        w._m_batch_size.observe(len(frame))
                        w._m_sends.inc()
                else:
                    ch.send(payload)
                    w._m_sends.inc()
            w.breaker.success(self.owner)
        except (OSError, ValueError):
            # OSError: stale address, dead peer, partition, send deadline.
            # ValueError: Channel.send's MAX_FRAME backstop — same
            # dead-channel class the serve loops treat it as; either way,
            # NEVER let it escape and kill this writer thread (a dead lane
            # would silently eat every future send to this peer).  Drop
            # the rest of this drain; OWNERS rewiring + the retry loop's
            # PEER_PULLs recover anything the peer still needs.
            w._drop_peer(self.owner)
            w.breaker.failure(self.owner)


def _ring_msg(tid: TileId, epoch: int, ring: Ring) -> dict:
    return {
        "type": P.PEER_RING,
        "tile": list(tid),
        "epoch": epoch,
        "top": ring.top,
        "bottom": ring.bottom,
        "left": ring.left,
        "right": ring.right,
        "corners": ring.corners,
    }


def _ring_of_msg(msg: dict) -> Ring:
    return Ring(
        top=msg["top"],
        bottom=msg["bottom"],
        left=msg["left"],
        right=msg["right"],
        corners=dict(msg["corners"]),  # (k, k) blocks, decoded as arrays
    )


class BackendWorker:
    """One worker process/thread: joins, hosts tiles, steps them, and serves
    its boundary rings to peer workers directly."""

    # Lock discipline (tools/graftlint, pass GL-LOCK01): the mutable shared
    # state each lock actually orders.  The worker RLock serializes the tile
    # table, wiring, and pause/target; the peer/sender/pre-stop locks own
    # their maps.  Set-once run config (rule, layout, store, cadences) is
    # deliberately undeclared: replaced atomically at (re)wiring, and
    # BoundaryStore is internally thread-safe.
    _GRAFTLINT_GUARDED = {
        "tiles": "_lock",
        "owners": "_lock",
        "_owner_map": "_lock",
        "paused": "_lock",
        "target": "_lock",
        "origins": "_lock",
        "_actor_engines": "_lock",
        "_peers": "_peer_lock",
        "_senders": "_sender_lock",
        "_serve_peer_addrs": "_lock",
        "_pre_stop_hooks": "_pre_stop_lock",
        "_pre_stop_done": "_pre_stop_lock",
    }

    def __init__(
        self,
        host: str,
        port: int,
        *,
        name: Optional[str] = None,
        engine: str = "jax",
        pallas: Optional[str] = None,
        retry_s: float = 1.0,
        retry_max_s: float = 8.0,
        max_pull_retries: int = 10,
        breaker_failures: int = 3,
        breaker_cooldown_s: float = 2.0,
        send_deadline_s: float = 0.0,
        ring_pack: bool = True,
        ring_batch: bool = True,
        ring_queue_depth: int = 1024,
        peer_host: str = "0.0.0.0",
        crash_hook: Optional[Callable[[], None]] = None,
        registry=None,
        tracer=None,
        netchaos: Optional[NetworkChaos] = None,
    ) -> None:
        if engine not in ("numpy", "jax", "swar", "actor", "actor-native"):
            raise ValueError(
                f"unknown engine {engine!r}; use numpy, jax, swar, actor, "
                f"or actor-native"
            )
        if engine in ("swar", "actor-native"):
            from akka_game_of_life_tpu.native import available, load_error

            if not available():
                raise RuntimeError(f"{engine} engine unavailable: {load_error()}")
        self.host = host
        self.port = port
        self.name = name
        self.engine = engine
        # Mosaic pin for the jax engine: None/"auto" promotes binary chunks
        # to the Pallas sweep on a real single-TPU worker, "off" pins the
        # XLA scan (the operator's escape hatch if Mosaic compiles but
        # regresses), "interpret" forces the sweep CPU-side (tests).
        self.pallas = pallas
        # Retry policy (cluster config, overridden by WELCOME): base
        # interval, backoff cap, and the per-tile budget before escalation.
        self.retry_s = retry_s
        self.retry_max_s = max(retry_s, retry_max_s)
        self.max_pull_retries = max_pull_retries
        self.send_deadline_s = send_deadline_s
        # Halo-plane wire policy (cluster config, overridden by WELCOME):
        # bit-pack binary rings on the wire, coalesce per-peer batches, and
        # bound each peer's async send queue.
        self.ring_pack = ring_pack
        self.ring_batch = ring_batch
        self.ring_queue_depth = max(1, int(ring_queue_depth))
        # Digest plane (cluster config, shipped in WELCOME): at digest-due
        # epochs (metrics/checkpoint cadence + final) each tile's 64-bit
        # fingerprint lanes ride the PROGRESS ping — O(tiles) bytes for the
        # frontend to certify cluster state, no board assembly anywhere.
        self.obs_digest = False
        # Quiescence tier (cluster config, shipped in WELCOME): skip the
        # step compute / ring payload / per-chunk PROGRESS ping of tiles
        # whose chunk input (state + halo) repeats (period 1 or 2).  Actor
        # engines are stateful and never skip regardless.
        self.sparse_cluster = False
        # Compile & cost observatory (cluster config, shipped in WELCOME's
        # "obs" bundle): cadence of the P.COST frames carrying this worker's
        # program-ledger summary (0 disables the loop) and the shared
        # profiler-capture policy for P.PROFILE fan-outs.  ``profile_dir``
        # is role wiring — run_backend points it at flight_dir so captures
        # land beside the crash dumps.
        self.cost_interval_s = 5.0
        self.profile_dir = "artifacts"
        self._obs_profile: Dict[str, float] = {}
        self._profiler = None
        # Decorrelated-jitter draws; reseeded per worker name in connect()
        # so a seeded cluster run's retry timing is reproducible per node.
        self._retry_rng = random.Random(f"retry:{name}")
        # Wire-fault policy (None = clean wire) and the per-peer breaker it
        # exercises; the breaker exists unconditionally — real dead peers
        # trip it with no chaos installed.
        self.netchaos = netchaos
        # DoCrashMsg → throw (CellActor.scala:95-96): default is an abrupt
        # process death; in-thread harnesses override to simulate it.
        self.crash_hook = crash_hook or (lambda: os._exit(42))

        # Worker-side observability: the peer data plane and the retry/
        # escalation machinery are exactly the paths the reference's log
        # stream never surfaced (how many rings flowed, how many pulls went
        # stale); counters make them first-class.
        reg = registry if registry is not None else get_registry()
        self.registry = reg
        # Tracing: step/halo/retry spans parent themselves under the trace
        # context the frontend embeds in TICK/DEPLOY envelopes, so a
        # frontend epoch span links to every chunk this worker steps for it.
        self.tracer = tracer if tracer is not None else get_tracer()
        self._trace_ctx: Optional[dict] = None
        self._m_sends = reg.counter("gol_peer_sends_total")
        self._m_receives = reg.counter("gol_peer_receives_total")
        self._m_retries = reg.counter("gol_peer_retries_total")
        self._m_wakeups = reg.counter("gol_retry_wakeups_total")
        self._m_drops = reg.counter("gol_peer_drops_total")
        self._m_heartbeats = reg.counter("gol_heartbeats_total")
        self._m_gather_failures = reg.counter("gol_gather_failures_total")
        self._m_ring_bytes = reg.counter("gol_ring_bytes_total")
        self._m_backoff = reg.histogram("gol_retry_backoff_seconds")
        # Halo wire-plane accounting: actual encoded bytes enqueued for the
        # wire (vs gol_ring_bytes_total's dense cell bytes — the packed/raw
        # ratio IS the packing win), rings per coalesced frame, and the
        # per-peer async queue's live depth / overflow drops.
        from akka_game_of_life_tpu.obs.catalog import RING_BATCH_BUCKETS

        self._m_packed_bytes = reg.counter("gol_ring_packed_bytes_total")
        self._m_batch_size = reg.histogram(
            "gol_ring_batch_size", buckets=RING_BATCH_BUCKETS
        )
        self._m_queue_depth = reg.gauge(
            "gol_peer_send_queue_depth",
            "Entries queued in a peer's async send lane",
            ("peer",),
        )
        self._m_queue_drops = reg.counter("gol_peer_send_queue_drops_total")
        # Quiescence-tier accounting: chunks this worker skipped outright,
        # O(1)-byte same-ring markers published in place of ring payloads,
        # and markers a receiver could not resolve (pruned ref — the
        # dependent pull re-asks and the real ring is served, so a miss is
        # latency, never corruption).
        self._m_skipped_chunks = reg.counter("gol_tile_chunks_skipped_total")
        self._m_same_markers = reg.counter("gol_ring_same_markers_total")
        self._m_same_misses = reg.counter("gol_ring_same_miss_total")
        self.breaker = CircuitBreaker(
            failures=breaker_failures,
            cooldown_s=breaker_cooldown_s,
            registry=reg,
            tracer=self.tracer,
            node=name or "backend",
        )

        self.tiles: Dict[TileId, _Tile] = {}
        # Cluster-sharded serving: constructed at WELCOME when the
        # frontend's serve plane is on — this worker then hosts session
        # shards in its own vmapped batch engine (serve/worker.py).
        self.serve_plane = None
        # Federation re-home targets (peer frontends' worker listeners),
        # installed from WELCOME and refreshed by FED_PEERS pushes.
        self._federation_fallbacks: List[Tuple[str, int]] = []
        self.rule: Optional[Rule] = None
        self.target = 0
        self.final_epoch = 0
        # Communication-avoiding exchange: rings/halos are this many cells
        # wide and one exchange buys this many local epochs (cluster-wide,
        # frontend-owned; arrives in WELCOME).
        self.exchange_width = 1
        self.render_every = 0
        self.checkpoint_every = 0
        self.metrics_every = 0
        self.render_strides: Tuple[int, int] = (1, 1)
        self.probe_window: Optional[Tuple[int, int, int, int]] = None
        self.origins: Dict[TileId, Tuple[int, int]] = {}
        self.paused = False
        self.channel: Optional[Channel] = None
        self._step_chunk: Optional[Callable[[np.ndarray, int, int], np.ndarray]] = None
        self._actor_engines: Dict[TileId, object] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self.stopped_reason: Optional[str] = None
        # Run-once hooks fired just before the control channel closes on
        # ANY orderly exit (SHUTDOWN, stop()) and on CRASH — the span
        # forwarder drains its pending batch here so the frontend's trace
        # file doesn't lose the run's final second.  Guarded by a dedicated
        # lock, NOT self._lock: the CRASH path runs these and must never
        # wait behind a compute step holding the worker lock.
        self._pre_stop_hooks: List[Callable[[], None]] = []
        self._pre_stop_lock = threading.Lock()
        self._pre_stop_done = False

        # -- peer-to-peer data plane -----------------------------------------
        self.layout: Optional[TileLayout] = None
        self.store: Optional[BoundaryStore] = None
        # tile → (owner name, host, port); OWNERS broadcasts keep it current
        self.owners: Dict[TileId, Tuple[str, str, int]] = {}
        self._peers: Dict[str, Channel] = {}  # dialed, by owner name
        self._peer_lock = threading.Lock()
        # Serve-plane peer addresses (resident tiled halo exchange): the
        # frontend names each chunk owner's peer endpoint in the step op,
        # so serve workers can dial each other without any OWNERS wiring.
        self._serve_peer_addrs: Dict[str, Tuple[str, int]] = {}
        # One async outbound lane per peer (bounded queue + writer thread);
        # created on first send to an owner, closed on stop/rewiring.
        self._senders: Dict[str, _PeerSender] = {}
        self._sender_lock = threading.Lock()
        # Publish-path cache, invariant between OWNERS/DEPLOY changes:
        # per local tile its remote owners, and per remote owner the set of
        # local tiles bordering it (the batch-seal expectation).  Rebuilt
        # lazily; None = stale.  Guarded by self._lock.
        self._owner_map: Optional[
            Tuple[Dict[TileId, List[str]], Dict[str, set]]
        ] = None
        self._peer_listener = socket.create_server((peer_host, 0))
        self.peer_port = self._peer_listener.getsockname()[1]
        threading.Thread(target=self._peer_accept_loop, daemon=True).start()

    # -- lifecycle -----------------------------------------------------------

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        sock.settimeout(None)
        self.channel = Channel(sock)
        if self.netchaos is not None and self.netchaos.config.wraps_control:
            # Control-plane chaos drops silently (fail_blocked=False): a
            # partitioned control link looks like a lossy wire, and the
            # heartbeat/eviction machinery — not an exception — judges it.
            self.channel = wrap_channel(
                self.channel, self.netchaos,
                src=self.name or "", dst="frontend",
            )
        self.channel.send(
            {
                "type": P.REGISTER,
                "name": self.name,
                "peer_port": self.peer_port,
                # The frontend rejects engines that can't honor the cluster's
                # exchange width (actor engines need per-epoch halos).
                "engine": self.engine,
                # Observability: the jax engine's Mosaic pin, so the
                # frontend's join line shows whether workers will step
                # Pallas chunks (auto resolves at first deploy).
                "pallas": self.pallas or "auto",
            }
        )
        welcome = self.channel.recv()
        if not welcome or welcome.get("type") != P.WELCOME:
            raise ConnectionError("frontend did not welcome us")
        self.name = welcome["name"]
        heartbeat_s = float(welcome.get("heartbeat_s", 0.5))
        # Retry/breaker/deadline policy is cluster config, owned by the
        # frontend (SimulationConfig); the constructor values are only the
        # standalone/test defaults — every worker of a cluster shares ONE
        # policy source of truth.
        if "max_pull_retries" in welcome:
            self.max_pull_retries = int(welcome["max_pull_retries"])
        if "retry_s" in welcome:
            self.retry_s = float(welcome["retry_s"])
        if "retry_max_s" in welcome:
            self.retry_max_s = max(self.retry_s, float(welcome["retry_max_s"]))
        if "breaker_failures" in welcome:
            self.breaker.failures = max(1, int(welcome["breaker_failures"]))
        if "breaker_cooldown_s" in welcome:
            self.breaker.cooldown_s = float(welcome["breaker_cooldown_s"])
        if "send_deadline_s" in welcome:
            self.send_deadline_s = float(welcome["send_deadline_s"])
        if "ring_pack" in welcome:
            self.ring_pack = bool(welcome["ring_pack"])
        if "ring_batch" in welcome:
            self.ring_batch = bool(welcome["ring_batch"])
        if "ring_queue_depth" in welcome:
            self.ring_queue_depth = max(1, int(welcome["ring_queue_depth"]))
        if "obs_digest" in welcome:
            self.obs_digest = bool(welcome["obs_digest"])
        if "sparse_cluster" in welcome:
            self.sparse_cluster = bool(welcome["sparse_cluster"])
        if "obs" in welcome:
            # Compile & cost observatory bundle: ledger on/off, COST frame
            # cadence, and the profiler-capture policy — one policy source
            # of truth (the frontend's SimulationConfig), like the retry
            # and wire bundles above.
            from akka_game_of_life_tpu.obs.programs import get_programs

            _obs = welcome.get("obs") or {}
            self.cost_interval_s = float(
                _obs.get("cost_interval_s", self.cost_interval_s)
            )
            self._obs_profile = {
                k: float(_obs[k])
                for k in ("max_s", "min_interval_s")
                if k in _obs
            }
            get_programs().configure(
                node=welcome.get("name") or self.name,
                enabled=bool(_obs.get("programs", True)),
            )
        # Federation fallbacks: peer frontends' worker listeners, the
        # re-home targets if THIS frontend dies (kept current via
        # FED_PEERS pushes).  Empty outside a federated cluster.
        self._federation_fallbacks = [
            (str(a[0]), int(a[1]))
            for a in welcome.get("federation") or []
            if isinstance(a, (list, tuple)) and len(a) == 2
        ]
        if welcome.get("serve_cluster"):
            from akka_game_of_life_tpu.serve.worker import ServeWorkerPlane

            # The serve knobs arrive in WELCOME like every other cluster
            # policy bundle; the plane owns a local SessionRouter (the PR 7
            # batch engine, unchanged) plus the op/shard wire glue.  The
            # plane sends through _control_send — a late-bound wrapper, so
            # a control-channel re-home after a frontend loss redirects
            # its frames without rebuilding the plane (sessions intact).
            self.serve_plane = ServeWorkerPlane(
                welcome.get("serve", {}),
                self._control_send,
                name=self.name or "",
                registry=self.registry,
                tracer=self.tracer,
                peer_send=self.serve_peer_send,
            )
        self._retry_rng = random.Random(f"retry:{self.name}")
        self.breaker.node = self.name or "backend"
        if isinstance(self.channel, ChaosChannel):
            self.channel.src = self.name or ""
        if self.send_deadline_s:
            self.channel.set_send_deadline(self.send_deadline_s)
        self.exchange_width = int(welcome.get("exchange_width", 1))
        threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_s,), daemon=True
        ).start()
        threading.Thread(target=self._retry_loop, daemon=True).start()
        if self.cost_interval_s > 0:
            threading.Thread(
                target=self._cost_loop, args=(self.cost_interval_s,),
                daemon=True,
            ).start()

    def run(self) -> int:
        """Blocking serve loop; returns when shut down or disconnected.

        ``_stop`` is set on every NORMAL exit (shutdown, EOF, wire error)
        but deliberately NOT when an interrupt tears out of the loop: the
        CLI's SIGTERM drain re-enters ``run()`` to keep serving the
        migration protocol, and the worker must still be alive for that —
        heartbeats beating (or the frontend would auto-down a draining
        member) and the control channel readable."""
        if self.channel is None:
            self.connect()
        try:
            while not self._stop.is_set():
                try:
                    msg = self.channel.recv()
                except (OSError, ValueError):
                    # Wire failure mid-read: in a federated cluster the
                    # frontend may have died while this worker's sessions
                    # live on — re-home the control channel instead of
                    # tearing the worker down.
                    if self._rehome():
                        continue
                    raise
                if msg is None:
                    if self._rehome():
                        continue
                    self.stopped_reason = self.stopped_reason or "disconnected"
                    break
                self._dispatch(msg)
            self._stop.set()
        except (OSError, ValueError) as e:
            # ValueError = a malformed frame from wire.recv (bad magic,
            # oversize claim, bad payload structure): same clean shutdown
            # as a connection error, with the reason on record.
            self.stopped_reason = self.stopped_reason or f"connection error ({e})"
            self._stop.set()
        except KeyboardInterrupt:
            # The SIGTERM drain path re-enters run(); the worker must stay
            # alive (heartbeats beating, control channel readable) or the
            # frontend would auto-down a draining member.
            raise
        except BaseException:
            # Any other escape (a dispatch handler bug, MemoryError, ...)
            # must still stop the heartbeat/retry daemons, or the frontend
            # keeps seeing a healthy member whose tiles never step again.
            self._stop.set()
            raise
        return 0 if self.stopped_reason in ("shutdown", "drained") else 1

    def _control_send(self, msg: dict) -> None:
        """Late-bound control-channel send: reads ``self.channel`` at call
        time, so the serve plane's bound sender follows a re-home instead
        of writing into a dead socket forever."""
        self.channel.send(msg)

    def _rehome(self) -> bool:
        """Control channel lost in a FEDERATED cluster: dial a surviving
        peer frontend from the FED_PEERS fallback list, re-REGISTER under
        the SAME name (sessions live in this process — nothing is lost),
        and announce the hosted session truth with ``SHARD_HOME`` so the
        adopting frontend closes its failover window.  Returns True when
        the worker is homed on a new frontend; False means a normal
        disconnect (not federated, stopping, or no fallback answered)."""
        if (
            self._stop.is_set()
            or self.serve_plane is None
            or not self._federation_fallbacks
        ):
            return False
        deadline = time.monotonic() + 15.0  # graftlint: waive GL-HAZ04 -- real-time re-home bound pairs with the sleep pacing below; an unreachable federation must fail finitely
        while time.monotonic() < deadline and not self._stop.is_set():
            for host, port in list(self._federation_fallbacks):
                if (host, port) == (self.host, self.port):
                    continue  # the frontend that just died
                try:
                    sock = socket.create_connection((host, port), timeout=3)
                    sock.settimeout(None)
                    channel = Channel(sock)
                    channel.send({
                        "type": P.REGISTER,
                        "name": self.name,
                        "peer_port": self.peer_port,
                        "engine": self.engine,
                        "pallas": self.pallas or "auto",
                    })
                    welcome = channel.recv()
                    if not welcome or welcome.get("type") != P.WELCOME:
                        channel.close()
                        continue
                except (OSError, ValueError):
                    continue
                # Swap BEFORE announcing: _control_send and the heartbeat
                # loop read self.channel at call time, so from here on
                # every serve frame rides the new home.
                old = self.channel
                self.channel = channel
                self.host, self.port = host, port
                if self.send_deadline_s:
                    channel.set_send_deadline(self.send_deadline_s)
                self._federation_fallbacks = [
                    (str(a[0]), int(a[1]))
                    for a in welcome.get("federation") or []
                    if isinstance(a, (list, tuple)) and len(a) == 2
                ] or self._federation_fallbacks
                try:
                    old.close()
                except OSError:
                    pass
                try:
                    channel.send({
                        "type": P.SHARD_HOME,
                        **self.serve_plane.home_summary(),
                    })
                except OSError:
                    continue  # the new home died instantly; keep trying
                print(
                    f"worker {self.name} re-homed control channel to "
                    f"{host}:{port}",
                    flush=True,
                )
                return True
            time.sleep(0.25)
        return False

    def _run_pre_stop_hooks(self) -> None:
        with self._pre_stop_lock:
            if self._pre_stop_done:
                return
            self._pre_stop_done = True
            hooks = list(self._pre_stop_hooks)
        for hook in hooks:
            try:
                hook()
            except Exception:  # noqa: BLE001 — shutdown must complete
                pass

    def stop(self) -> None:
        self._stop.set()
        self._run_pre_stop_hooks()
        if self.serve_plane is not None:
            # Before the control channel closes: the plane's reply thread
            # writes there, and its router must stop ticking.
            self.serve_plane.close()
        if self.channel is not None:
            try:
                # Graceful leave (cluster down): distinguishable from a crash.
                self.channel.send({"type": P.GOODBYE})
            except OSError:
                pass
            self.channel.close()
        try:
            self._peer_listener.close()
        except OSError:
            pass
        with self._sender_lock:
            senders = list(self._senders.values())
            self._senders.clear()
        for s in senders:
            s.close()
        with self._peer_lock:
            for ch in self._peers.values():
                ch.close()
            self._peers.clear()

    # -- peer plumbing ---------------------------------------------------------

    def _peer_accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = self._peer_listener.accept()
            except OSError:
                return
            ch = Channel(sock, send_deadline_s=self.send_deadline_s)
            if self.netchaos is not None and self.netchaos.config.wraps_peer:
                # dst is learned from the PEER_HELLO (see _on_peer_msg);
                # until then the wrapper applies only the probabilistic
                # faults, not partition sides.
                ch = wrap_channel(
                    ch, self.netchaos,
                    src=self.name or "", fail_blocked=True,
                )
            threading.Thread(
                target=self._serve_peer, args=(ch,), daemon=True
            ).start()

    def _serve_peer(self, channel: Channel) -> None:
        try:
            while not self._stop.is_set():
                msg = channel.recv()
                if msg is None:
                    return
                self._on_peer_msg(msg, channel)
        except OSError:
            pass
        except ValueError as e:
            # Malformed frame or un-decodable ring entry (the mixed-version
            # case): the fail-LOUD contract — name the reason and kill the
            # link, so the far end sees a dropped peer (breaker, re-dial)
            # instead of a silently deaf socket nobody reads.
            print(
                f"{self.name or 'backend'}: dropping peer channel: {e}",
                flush=True,
            )
            with self._peer_lock:
                owner = next(
                    (k for k, v in self._peers.items() if v is channel), None
                )
            if owner is not None:
                self._drop_peer(owner)
            else:
                try:
                    channel.close()
                except OSError:
                    pass

    def _on_peer_msg(self, msg: dict, channel: Channel) -> None:
        kind = msg.get("type")
        if kind == P.PEER_HELLO:
            # Adopt the incoming channel for our own pushes to that peer —
            # peer links are symmetric, so one TCP connection per pair.
            name = msg.get("name")
            if name:
                if isinstance(channel, ChaosChannel):
                    # Now we know who the far end is: partition sides apply.
                    channel.dst = name
                    self.netchaos.register_node(name)
                with self._peer_lock:
                    self._peers.setdefault(name, channel)
        elif kind == P.PEER_RING:
            self._m_receives.inc()
            if self.store is not None:
                if "same_as" in msg:
                    # Quiescent peer: the ring repeats the one it published
                    # at same_as — resolve from the local store, zero
                    # payload bytes.  A miss (ref pruned here) is dropped;
                    # the dependent pull's retry re-asks and the owner
                    # serves the real ring from its own store.
                    ring = self.store.ring_at(
                        tuple(msg["tile"]), int(msg["same_as"])
                    )
                    if ring is None:
                        self._m_same_misses.inc()
                        return
                elif "ring" in msg:
                    ring = decode_ring(msg["ring"])
                else:
                    ring = _ring_of_msg(msg)
                # push_ring fires queued local pull callbacks (_apply_halo),
                # so the span also covers any tile chunks this ring unblocks.
                with self.tracer.span(
                    "halo.recv", parent=self._trace_ctx,
                    node=self.name or "backend", tile=str(tuple(msg["tile"])),
                    epoch=int(msg["epoch"]),
                ):
                    self.store.push_ring(
                        tuple(msg["tile"]), int(msg["epoch"]), ring
                    )
        elif kind == P.PEER_RING_BATCH:
            entries = msg.get("rings") or []
            if not entries or self.store is None:
                return  # an empty batch frame is a no-op, not an error
            self._m_receives.inc(len(entries))
            # Decode + store the WHOLE batch before any unblocked tile
            # steps (push_rings fires callbacks after the last store), so
            # dependent tiles step back-to-back and their outbound rings
            # coalesce in turn.  A malformed entry raises ValueError —
            # the serve loop drops the peer connection, loudly.  Quiescence
            # markers ("same_as") resolve against the local store; an
            # unresolvable one is dropped (miss counted) and recovered by
            # the dependent pull's re-ask.
            items = []
            for e in entries:
                if "same_as" in e:
                    ring = self.store.ring_at(
                        tuple(e["tile"]), int(e["same_as"])
                    )
                    if ring is None:
                        self._m_same_misses.inc()
                        continue
                else:
                    ring = decode_ring(e["ring"])
                items.append((tuple(e["tile"]), int(e["epoch"]), ring))
            if not items:
                return
            with self.tracer.span(
                "halo.recv", parent=self._trace_ctx,
                node=self.name or "backend", rings=len(items),
                epoch=items[0][1],
            ):
                self.store.push_rings(items)
        elif kind in (P.TILED_HALO, P.TILED_HALO_ACK):
            # Resident tiled-session halo exchange: the frame rides the
            # serve plane's op FIFO, so a strip orders against its
            # session's install/step/migration ops like any other op.
            if self.serve_plane is not None:
                self.serve_plane.handle(msg)
        elif kind == P.PEER_PULL:
            # Serve every ring we have from the asked epoch forward, for
            # EVERY tile the peer asks about (one frame asks a whole
            # neighborhood): a redeployed neighbor replaying from a
            # checkpoint streams its catch-up window in one exchange
            # instead of one round-trip per tile per epoch.
            epoch = int(msg["epoch"])
            tiles = [tuple(t) for t in (msg.get("tiles") or [msg["tile"]])]
            if self.store is None:
                return
            served: List[Tuple[TileId, int, Ring]] = []
            for tile in tiles:
                served.extend(
                    (tile, e, ring) for e, ring in self.store.rings_from(tile, epoch)
                )
            if not served:
                return
            pack = (
                self.ring_pack and self.rule is not None and self.rule.is_binary
            )
            with self.tracer.span(
                "halo.serve", parent=self._trace_ctx,
                node=self.name or "backend", tiles=len(tiles), epoch=epoch,
                rings=len(served),
            ):
                try:
                    if self.ring_batch:
                        entries = [
                            {
                                "tile": list(tile),
                                "epoch": e,
                                "ring": encode_ring(ring, pack),
                            }
                            for tile, e, ring in served
                        ]
                        for frame in split_ring_batches(entries):
                            channel.send(
                                {"type": P.PEER_RING_BATCH, "rings": frame}
                            )
                            self._m_batch_size.observe(len(frame))
                            self._m_sends.inc()
                    else:
                        for tile, e, ring in served:
                            channel.send(
                                {
                                    "type": P.PEER_RING,
                                    "tile": list(tile),
                                    "epoch": e,
                                    "ring": encode_ring(ring, pack),
                                }
                                if pack
                                else _ring_msg(tile, e, ring)
                            )
                            self._m_sends.inc()
                except OSError:
                    return

    def _peer_channel(self, owner: str) -> Optional[Channel]:
        """The dialed channel to a peer worker, connecting on first use."""
        entry = self.owners_by_name().get(owner)
        if entry is None:
            return None
        host, port = entry
        with self._peer_lock:
            ch = self._peers.get(owner)
            if ch is not None:
                return ch
            try:
                sock = socket.create_connection((host, port), timeout=5)
                sock.settimeout(None)
            except OSError:
                return None
            ch = Channel(sock, send_deadline_s=self.send_deadline_s)
            if self.netchaos is not None and self.netchaos.config.wraps_peer:
                ch = wrap_channel(
                    ch, self.netchaos,
                    src=self.name or "", dst=owner, fail_blocked=True,
                )
            self._peers[owner] = ch
        # Peer channels are bidirectional: the accepting side serves our
        # PEER_PULLs and may push rings back on the same socket.
        threading.Thread(target=self._serve_peer, args=(ch,), daemon=True).start()
        try:
            ch.send({"type": P.PEER_HELLO, "name": self.name})
        except OSError:
            self._drop_peer(owner)
            return None
        return ch

    def _drop_peer(self, owner: str) -> None:
        with self._peer_lock:
            ch = self._peers.pop(owner, None)
        if ch is not None:
            ch.close()
            self._m_drops.inc()

    def owners_by_name(self) -> Dict[str, Tuple[str, int]]:
        with self._lock:
            out = dict(self._serve_peer_addrs)
            out.update(
                (name, (host, port))
                for name, host, port in self.owners.values()
            )
            return out

    def serve_peer_send(self, name: str, host: str, port: int, msg: dict) -> None:
        """Queue a serve-plane frame (TILED_HALO / ..._ACK) toward a peer
        worker named by the frontend — same async per-peer lane as ring
        traffic, with the address learned from the op instead of OWNERS."""
        with self._lock:
            self._serve_peer_addrs[name] = (host, int(port))
        self._send_peer(name, msg)

    def _sender(self, owner: str) -> Optional[_PeerSender]:
        """The async outbound lane to a peer, created on first use — or
        None for an owner no longer in the wiring.  The membership check
        runs INSIDE the creation critical section: a publish that
        snapshotted its owner set just before an OWNERS rewiring must not
        resurrect the departed peer's lane after the rewiring reaped it
        (leaked writer thread + gauge series dialing a stale address).
        Lock order _sender_lock → worker lock is acyclic: no path holds
        the worker lock while taking _sender_lock."""
        with self._sender_lock:
            s = self._senders.get(owner)
            if s is None:
                with self._lock:
                    known = {name for name, _, _ in self.owners.values()}
                    known |= set(self._serve_peer_addrs)
                if known and owner not in known:
                    return None
                s = self._senders[owner] = _PeerSender(self, owner)
            return s

    def _send_peer(self, owner: str, msg: dict) -> None:
        """Queue a control message for ``owner``'s writer thread.  Never
        touches the socket: dialing, the circuit breaker, and failure
        handling all run on the peer's writer (``_PeerSender._send``), so
        no compute or serve thread can block on a wedged link."""
        s = self._sender(owner)
        if s is not None:
            s.enqueue_msg(msg)

    # -- helper threads ------------------------------------------------------

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._stop.is_set():
            time.sleep(interval)
            try:
                self.channel.send({"type": P.HEARTBEAT})
                self._m_heartbeats.inc()
            except OSError:
                # Federated worker: the run() loop may be mid-re-home onto
                # a surviving frontend — keep this ONE loop alive (it reads
                # self.channel at each send, so it follows the swap) rather
                # than racing a restarted thread against it.
                if self.serve_plane is not None and self._federation_fallbacks:
                    continue
                return

    def _cost_loop(self, interval: float) -> None:
        """Low-cadence P.COST shipping: this worker's program-ledger
        summary (compile counts, per-family throughput, device memory
        watermarks) rides to the frontend, which merges every member into
        one cluster ``/cost`` view.  The local device gauges refresh here
        too, so the worker's own /metrics exposition carries live
        watermarks between metric dumps."""
        from akka_game_of_life_tpu.obs.programs import get_programs

        while not self._stop.wait(interval):
            programs = get_programs()
            try:
                programs.refresh_device_gauges()
            except Exception:
                pass
            try:
                self.channel.send({"type": P.COST, **programs.summary()})
            except OSError:
                return

    def _profile_capture(self, prof, seconds) -> None:
        try:
            want = float(seconds) if seconds is not None else None
        except (TypeError, ValueError):
            want = None
        result = prof.capture(want)
        if result.get("ok"):
            print(
                f"profiler capture: {result.get('artifact')} "
                f"({result.get('seconds')}s)",
                flush=True,
            )
        else:
            # A fanned capture has no HTTP response to carry the error —
            # the worker log is the only place the operator can see it.
            print(
                f"profiler capture failed: {result.get('error')} "
                f"(status {result.get('status')})",
                flush=True,
            )

    def _retry_loop(self) -> None:
        """The gatherer's Retry timer: re-ask the owners of missing rings.

        Hardened pacing: the first re-ask fires ``retry_s`` after the pull
        queued; each further consecutive re-ask of the same tile backs off
        with decorrelated jitter — ``delay = min(retry_max_s,
        uniform(retry_s, 3 * last_delay))`` — so a partitioned or lossy
        neighborhood sees a handful of desynchronized probes per cooling
        window instead of every stale tile re-asking in lockstep each
        ``retry_s`` (the retry-storm that makes heal moments worse than the
        fault).  A successful pull resets the tile's delay to the base.

        After ``max_pull_retries`` unanswered re-asks the worker escalates
        with GATHER_FAILED — the reference's gatherer gives up after 2 ask
        rounds and fires ``FailedToGatherInfoMsg`` so its parent repairs the
        neighborhood (``NextStateCellGathererActor.scala:49-58``,
        ``CellActor.scala:92-94``).  The tile keeps its state and keeps
        retrying; the frontend decides whether a blocking neighbor is
        genuinely stuck."""
        while not self._stop.wait(max(0.01, self.retry_s / 4)):
            now = time.monotonic()
            failed: List[Tuple[TileId, int]] = []
            stale: List[Tuple[TileId, int]] = []
            thawed: List[TileId] = []
            delays: List[float] = []
            with self._lock:
                if self.paused:
                    continue
                for tid, t in self.tiles.items():
                    if t.frozen_until:
                        # Migration freeze: no re-asks, no escalation — the
                        # tile is deliberately still.  Past the deadline the
                        # move evidently failed mid-protocol; unfreeze and
                        # resume (the frontend's abort already cooled the
                        # tile down on its side).
                        if now < t.frozen_until:
                            continue
                        t.frozen_until = 0.0
                        thawed.append(tid)
                        continue
                    if t.awaiting_since is None or now < t.next_retry_at:
                        continue
                    t.retries += 1
                    if t.retries > self.max_pull_retries:
                        t.retries = 0  # re-arm: escalate again if still stuck
                        failed.append((tid, t.epoch))
                    t.retry_delay = min(
                        self.retry_max_s,
                        self._retry_rng.uniform(self.retry_s, 3 * t.retry_delay),
                    )
                    t.next_retry_at = now + t.retry_delay
                    delays.append(t.retry_delay)
                    stale.append((tid, t.epoch))
            for tid in thawed:
                # Resume a tile whose migration never concluded: re-drive so
                # it re-pulls its halo (rings are still in the local store —
                # the prune floor could not pass a tile that stopped moving).
                self._drive(tid)
            for d in delays:
                self._m_backoff.observe(d)
            if stale:
                # One wakeup that found work; one retry per stale tile.
                self._m_wakeups.inc()
                self._m_retries.inc(len(stale))
                with self.tracer.span(
                    "halo.retry", parent=self._trace_ctx,
                    node=self.name or "backend", tiles=len(stale),
                    epochs=str([e for _, e in stale]),
                ):
                    for tid, epoch in stale:
                        self._ask_missing(tid, epoch)
            for tid, epoch in failed:
                with self.tracer.span(
                    "gather.escalate", parent=self._trace_ctx,
                    node=self.name or "backend", tile=str(tid), epoch=epoch,
                ):
                    try:
                        self.channel.send(
                            {
                                "type": P.GATHER_FAILED,
                                "tile": list(tid),
                                "epoch": epoch,
                            }
                        )
                        self._m_gather_failures.inc()
                    except OSError:
                        pass

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, msg: dict) -> None:
        kind = msg.get("type")
        if kind in (P.DEPLOY, P.TICK, P.CRASH, P.CRASH_TILE):
            # Adopt the frontend's span context: everything this worker does
            # from here until the next announcement is caused by it.  Plain
            # attribute store, NO worker lock: a compute step holds that
            # lock for whole chunks, and the CRASH path below must stay
            # abrupt — it cannot queue behind a multi-second step.
            ctx = extract_trace(msg)
            if ctx is not None:
                self._trace_ctx = ctx
        if kind == P.DEPLOY:
            self._on_deploy(msg)
        elif kind == P.OWNERS:
            self._on_owners(msg)
        elif kind == P.TICK:
            with self._lock:
                self.target = int(msg["target"])
            self._kick()
        elif kind == P.PRUNE:
            if self.store is not None:
                self.store.prune_below(int(msg["floor"]))
        elif kind == P.PAUSE:
            with self._lock:
                self.paused = True
        elif kind == P.RESUME:
            with self._lock:
                self.paused = False
            self._kick()
        elif kind == P.CRASH:
            # The post-mortem artifact BEFORE dying: the default crash_hook
            # is os._exit, so this dump is the node's last act.
            with self.tracer.span(
                "backend.crash", parent=self._trace_ctx, node=self.name or "backend",
                mode="node",
            ):
                self.tracer.flight.dump("crash", node=self.name or "backend")
            # Drain pending forwarded spans (including the backend.crash one
            # just finished) while the socket is still open — the default
            # crash_hook is os._exit, which would strand the 1 s flush batch
            # and leave the frontend trace without the victim's last second.
            self._run_pre_stop_hooks()
            self.crash_hook()
        elif kind == P.CRASH_TILE:
            with self.tracer.span(
                "backend.crash", parent=self._trace_ctx, node=self.name or "backend",
                mode="tile", tile=str(tuple(msg["tile"])),
            ):
                self.tracer.flight.dump("tile_crash", node=self.name or "backend")
                self._on_crash_tile(tuple(msg["tile"]))
        elif kind == P.MIGRATE_PREPARE:
            self._on_migrate_prepare(msg)
        elif kind == P.MIGRATE_ABORT:
            self._on_migrate_abort(tuple(msg["tile"]))
        elif kind in (
            P.SERVE_OPS, P.SHARD_PREPARE, P.SHARD_COMMIT, P.SHARD_ABORT,
            P.SHARD_REPLICATE_ACK,
        ):
            # Serve-plane frames enqueue to the plane's executor and never
            # block this reader: a step op's batch tick must not stall
            # heartbeat-adjacent control traffic.
            if self.serve_plane is not None:
                self.serve_plane.handle(msg)
        elif kind == P.FED_PEERS:
            # Federation peer set changed: refresh the control re-home
            # fallback list (workers that registered before the federation
            # converged learn their fallbacks through this push).
            self._federation_fallbacks = [
                (str(a[0]), int(a[1]))
                for a in msg.get("peers") or []
                if isinstance(a, (list, tuple)) and len(a) == 2
            ]
        elif kind == P.PROFILE:
            # Cluster profiler fan-out: the capture runs on a daemon
            # thread — a multi-second jax.profiler window must never block
            # this control reader.  Built lazily here (the dispatch loop is
            # single-threaded, so no lock): in-process test harnesses never
            # pay for a profiler they don't poke.
            if self._profiler is None:
                from akka_game_of_life_tpu.runtime.profiling import (
                    ProfilerCapture,
                )

                self._profiler = ProfilerCapture(
                    self.profile_dir,
                    node=self.name or "backend",
                    max_seconds=float(self._obs_profile.get("max_s", 30.0)),
                    min_interval_s=float(
                        self._obs_profile.get("min_interval_s", 60.0)
                    ),
                )
            threading.Thread(
                target=self._profile_capture,
                args=(self._profiler, msg.get("seconds")),
                daemon=True,
            ).start()
        elif kind == P.DRAIN_COMPLETE:
            # The frontend released us: either every tile migrated off
            # (drained=True → rc 0) or the drain was refused (no placeable
            # destination → the caller falls back to the abrupt-leave path).
            drained = bool(msg.get("drained", True))
            self.stopped_reason = "drained" if drained else "drain_refused"
            self._stop.set()
            self._run_pre_stop_hooks()
            if self.serve_plane is not None:
                self.serve_plane.close()
            try:
                # Deliberate leave, distinguishable from a crash — by now we
                # own nothing, so the frontend evicts without redeploying.
                self.channel.send({"type": P.GOODBYE})
            except OSError:
                pass
            self.channel.close()
        elif kind == P.SHUTDOWN:
            self.stopped_reason = "shutdown"
            self._stop.set()
            # Last words while the socket is still open (span-batch drain).
            self._run_pre_stop_hooks()
            if self.serve_plane is not None:
                self.serve_plane.close()
            self.channel.close()

    def _on_owners(self, msg: dict) -> None:
        """Ownership/wiring update — the reference's NeighboursRefs re-send
        (``BoardCreator.scala:149-151``)."""
        grid = tuple(msg["grid"])
        shape = tuple(msg["shape"])
        dropped: List[TileId] = []
        with self._lock:
            if self.layout is None or self.layout.grid != grid:
                self.layout = TileLayout(shape, grid)
                self.store = BoundaryStore(self.layout, self.exchange_width)
            self.owners = {
                tuple(t): (name, host, int(port))
                for t, name, host, port in msg["tiles"]
            }
            # Tiles moved away from us (e.g. judged stuck and re-placed):
            # stop stepping them; the new owner replays from the checkpoint.
            for tid in [t for t in self.tiles if self.owners.get(t, ("",))[0] != self.name]:
                del self.tiles[tid]
                self._actor_engines.pop(tid, None)
                dropped.append(tid)
            self._owner_map = None  # wiring changed: publish cache is stale
        if dropped and self.store is not None:
            for tid in dropped:
                self.store.drop_pending_for_owner([tid])
        # Breaker hygiene: a peer that left the cluster (evicted, renamed)
        # must not leave an open breaker behind — its gauge would read open
        # forever and its breaker.open span would never finish.  Names still
        # in the wiring keep their state (an open breaker on a live-but-dead
        # link is exactly what the half-open probes are for).
        with self._lock:
            owner_names = {name for name, _, _ in self.owners.values()}
        for peer in set(self.breaker.peers()) - owner_names:
            self.breaker.reset(peer)
        # Same hygiene for the async send lanes: a departed peer's writer
        # thread (and anything still queued for it) must not outlive the
        # wiring that named it.
        with self._sender_lock:
            gone = [o for o in self._senders if o not in owner_names]
            senders = [self._senders.pop(o) for o in gone]
        for s in senders:
            s.close()

    def _on_deploy(self, msg: dict) -> None:
        outbound: List[Tuple[TileId, np.ndarray, int]] = []
        seed_rings: List[Tuple[TileId, int, Ring]] = []
        with self._lock:
            rule = resolve_rule(msg["rule"])
            if rule.radius != 1:
                # The invariant lives here, not only at the Frontend: every
                # chunk engine below (swar C++, np peel, jax scan) assumes a
                # one-cell-per-step garbage front; a radius-R rule reaching
                # them would be silently wrong, not slow.
                raise ValueError(
                    f"cluster workers exchange radius-1 rings; cannot host "
                    f"{rule}"
                )
            if self.rule != rule:
                self.rule = rule
                if self.engine == "jax":
                    self._step_chunk = _jax_engine(rule, pallas=self.pallas)
                elif self.engine == "swar":
                    from akka_game_of_life_tpu.native.engine import (
                        swar_chunk_native,
                        swar_gen_chunk_native,
                        swar_wire_chunk_native,
                    )

                    if rule.is_binary and rule.is_totalistic:
                        self._step_chunk = (
                            lambda padded, steps, halo: swar_chunk_native(
                                padded, steps, halo, rule
                            )
                        )
                    elif rule.kind == "wireworld":
                        # The 2-bit-plane C++ twin (swar_wire_chunk).
                        self._step_chunk = (
                            lambda padded, steps, halo: swar_wire_chunk_native(
                                padded, steps, halo, rule
                            )
                        )
                    elif rule.is_totalistic:
                        # Generations: the m-plane C++ twin (swar_gen_chunk;
                        # Rule() caps states at 255, so no extra gate).
                        self._step_chunk = (
                            lambda padded, steps, halo: swar_gen_chunk_native(
                                padded, steps, halo, rule
                            )
                        )
                    else:
                        self._step_chunk = (
                            lambda padded, steps, halo: _np_chunk(
                                padded, steps, halo, rule
                            )
                        )
                elif self.engine == "numpy":
                    self._step_chunk = (
                        lambda padded, steps, halo: _np_chunk(padded, steps, halo, rule)
                    )
                # engine == "actor": stateful per-tile engines, built below
            self.target = int(msg["target"])
            self.final_epoch = int(msg["final_epoch"])
            self.render_every = int(msg.get("render_every", 0))
            self.checkpoint_every = int(msg.get("checkpoint_every", 0))
            self.metrics_every = int(msg.get("metrics_every", 0))
            self.render_strides = tuple(msg.get("render_strides", (1, 1)))
            pw = msg.get("probe_window")
            self.probe_window = tuple(pw) if pw is not None else None
            for spec in msg["tiles"]:
                tid: TileId = tuple(spec["id"])
                tile = _Tile(
                    unpack_tile(spec["state"]), int(spec["epoch"]),
                    retry_s=self.retry_s,
                )
                self.tiles[tid] = tile
                self.origins[tid] = tuple(spec.get("origin", (0, 0)))
                if self.engine == "actor":
                    # A (re)deploy is a supervision restart: fresh actors,
                    # histories reseeded from the deployed array.
                    from akka_game_of_life_tpu.runtime.actor_engine import (
                        ActorTileEngine,
                    )

                    self._actor_engines[tid] = ActorTileEngine(rule)
                elif self.engine == "actor-native":
                    from akka_game_of_life_tpu.native.engine import (
                        NativeActorTileEngine,
                    )

                    self._actor_engines[tid] = NativeActorTileEngine(rule)
                outbound.append((tid, tile.arr, tile.epoch))
                for e in spec.get("rings") or []:
                    seed_rings.append(
                        (tuple(e["tile"]), int(e["epoch"]), decode_ring(e["ring"]))
                    )
            self._owner_map = None  # tiles (re)deployed: publish cache is stale
        if seed_rings and self.store is not None:
            # A migrated tile arrives at its LIVE epoch; neighbors replaying
            # older epochs ask US (the new owner) for rings we never
            # computed.  The previous owner's retained ring history rode the
            # certified payload (it may already be out of the wiring — or
            # gone entirely, on a drain's final move — so a pull could never
            # be addressed); seeding it here also answers any local pulls
            # already queued on those epochs.
            self.store.push_rings(seed_rings)
        for tid, arr, epoch in outbound:
            # Announce our boundary at the deployed epoch so neighbors can
            # assemble their halos (History seeding, CellActor.scala:34).
            self._publish_ring(tid, arr, epoch)
            self._report_state(tid, arr, epoch)
        self._kick()

    def _on_crash_tile(self, tid: TileId) -> None:
        """Supervision-restart analog: the tile's in-memory state is lost;
        ask the parent to redeploy (postRestart → SendMeMyNeighbours,
        CellActor.scala:21-25)."""
        with self._lock:
            if tid in self.tiles:
                del self.tiles[tid]
            self._actor_engines.pop(tid, None)
            self._owner_map = None  # tile dropped: publish cache is stale
        try:
            self.channel.send({"type": P.REDEPLOY_REQUEST, "tile": list(tid)})
        except OSError:
            pass

    # -- live migration / drain (the elastic plane) --------------------------

    def _migrate_payload(self, tid: TileId, arr: np.ndarray, epoch: int) -> dict:
        """The MIGRATE_STATE body for one frozen tile: its bit-packed state
        (the PR 4 wire codec — 8 cells/byte for binary rules), the
        source-side digest lanes the frontend certifies the payload
        against, and the tile's retained ring history.  The history rides
        IN-BAND because the destination cannot reliably pull it later: a
        drain's final move removes the source from the OWNERS wiring (and
        the source may exit) before any pull could be addressed, yet
        lagging neighbors still re-ask the NEW owner for rings the new
        owner never computed.  Factored out so failure-path tests can
        corrupt it."""
        from akka_game_of_life_tpu.ops import digest as odigest

        with self._lock:
            origin = self.origins.get(tid, (0, 0))
            width = (
                self.layout.board_shape[1]
                if self.layout is not None
                else arr.shape[1]
            )
            store = self.store
        lanes = odigest.digest_dense_np(arr, origin, width)
        pack = self.ring_pack and self.rule is not None and self.rule.is_binary
        rings = (
            [
                {"tile": list(tid), "epoch": e, "ring": encode_ring(ring, pack)}
                for e, ring in store.rings_from(tid, 0)
            ]
            if store is not None
            else []
        )
        return {
            "type": P.MIGRATE_STATE,
            "tile": list(tid),
            "epoch": epoch,
            "state": pack_tile(arr),
            "digest": [int(lanes[0]), int(lanes[1])],
            "rings": rings,
        }

    def _on_migrate_prepare(self, msg: dict) -> None:
        """PREPARE: freeze the tile at its current chunk boundary and ship
        its state.  Compute runs under the worker lock, so the (arr, epoch)
        snapshot below is always a consistent chunk-boundary state; setting
        ``frozen_until`` under the same lock guarantees no later chunk
        starts.  A tile we no longer host is simply not answered — the
        frontend's migration deadline aborts the move."""
        tid: TileId = tuple(msg["tile"])
        seq = int(msg["seq"])
        deadline_s = float(msg.get("deadline_s", 10.0))
        with self._lock:
            tile = self.tiles.get(tid)
            if tile is None:
                return
            # 2× the frontend deadline: the frontend always decides first
            # (commit or abort); this is only the lost-message backstop.
            tile.frozen_until = time.monotonic() + 2.0 * deadline_s
            arr, epoch = tile.arr, tile.epoch
        out = self._migrate_payload(tid, arr, epoch)
        out["seq"] = seq
        try:
            self.channel.send(out)
        except OSError:
            pass
        except ValueError as e:
            # An oversize MIGRATE_STATE frame (tile state + ring history
            # past MAX_FRAME) must not escape into run()'s wire-error
            # handling and kill the whole worker — that would turn a
            # graceful drain of a healthy worker into node loss.  The
            # transfer can never happen, so unfreeze now instead of
            # waiting out the 2x-deadline thaw; the frontend's deadline
            # aborts the move on its side.
            print(
                f"tile {tid}: migration payload unsendable ({e}); "
                f"resuming",
                flush=True,
            )
            with self._lock:
                tile = self.tiles.get(tid)
                if tile is not None:
                    tile.frozen_until = 0.0
            self._drive(tid)

    def _on_migrate_abort(self, tid: TileId) -> None:
        """Rollback: unfreeze and resume stepping — the tile never left."""
        with self._lock:
            tile = self.tiles.get(tid)
            if tile is None:
                return
            tile.frozen_until = 0.0
        self._drive(tid)

    def request_drain(self) -> bool:
        """Ask the frontend to migrate every tile off this worker so it can
        leave without tripping node-loss recovery.  Returns False when a
        drain is pointless (no tiles, not connected, already stopping) —
        callers then take the abrupt-leave path.  The caller keeps serving
        the control channel; the frontend answers with MIGRATE_PREPAREs and
        finally DRAIN_COMPLETE."""
        with self._lock:
            has_tiles = bool(self.tiles)
        # A serve-shard host with no tiles still drains: its sessions must
        # migrate off before it may leave without losing tenant boards.
        serving = self.serve_plane is not None
        if (
            (not has_tiles and not serving)
            or self.channel is None
            or self._stop.is_set()
        ):
            return False
        try:
            self.channel.send({"type": P.DRAIN_REQUEST})
        except OSError:
            return False
        return True

    # -- stepping plumbing ---------------------------------------------------

    def _kick(self) -> None:
        """Start the drive loop for every tile that is behind and not
        already waiting (scheduleTransitionToNextepochIfNeeded,
        CellActor.scala:41-47).  Must be called WITHOUT the lock held — the
        drive loop sends to peer sockets, and no thread may hold its worker
        lock while writing into another worker (deadlock discipline)."""
        with self._lock:
            tids = list(self.tiles)
        for tid in tids:
            self._drive(tid)

    def _drive(self, tid: TileId) -> None:
        """Advance a tile while halos are immediately available, registering
        one queued pull when they are not.  Iterative on purpose: a tile
        replaying thousands of epochs against already-present rings must not
        recurse once per epoch."""
        while True:
            with self._lock:
                tile = self.tiles.get(tid)
                if (
                    tile is None
                    or self.store is None
                    or self.paused
                    or tile.awaiting_since is not None  # pull already in flight
                ):
                    return
                if tile.frozen_until:
                    if time.monotonic() < tile.frozen_until:
                        return  # migration in flight: state must not move
                    tile.frozen_until = 0.0  # deadline passed: self-heal
                # Chunked advance: one width-k halo exchange licenses the
                # next c = min(k, final-epoch) epochs; the tile waits until
                # the target covers the WHOLE chunk so every tile visits the
                # same epoch grid {0, k, 2k, ..., final} regardless of TICK
                # arrival order (mixed chunk boundaries would ask neighbors
                # for rings at epochs they never computed).
                c = self._chunk_for(tile.epoch)
                if c <= 0 or self.target < tile.epoch + c:
                    return
                epoch = tile.epoch
                # The waitingForNewState latch (CellActor.scala:32): set
                # before the pull so concurrent kicks don't double-drive.
                tile.awaiting_since = time.monotonic()
                tile.next_retry_at = tile.awaiting_since + self.retry_s
            halo = self.store.pull_halo_now(
                tid, epoch, lambda h, e=epoch: self._on_halo_ready(tid, e, h)
            )
            if halo is None:
                # Queued: the last PEER_RING's push will resume us.  Ask the
                # missing rings' owners right away (first-pull latency; the
                # retry loop is only the loss backstop).
                self._ask_missing(tid, epoch)
                return
            if not self._step_tile(tid, epoch, halo):
                return

    def _ask_missing(self, tid: TileId, epoch: int) -> None:
        # One PEER_PULL frame per owner, carrying EVERY missing tile of
        # that owner — the ask side of the coalescing contract (replies
        # batch the same way), so a stale neighborhood costs O(peers)
        # frames, not O(missing rings).
        asks: Dict[str, List[list]] = {}
        with self._lock:
            if self.store is None:
                return
            for ntile in self.store.missing_neighbor_rings(tid, epoch):
                entry = self.owners.get(ntile)
                if entry is not None and entry[0] != self.name:
                    asks.setdefault(entry[0], []).append(list(ntile))
        for owner, tiles in asks.items():
            self._send_peer(
                owner, {"type": P.PEER_PULL, "tiles": tiles, "epoch": epoch}
            )

    def _on_halo_ready(self, tid: TileId, epoch: int, halo: Halo) -> None:
        """Queued-pull completion, on whichever thread pushed the last ring."""
        if self._step_tile(tid, epoch, halo):
            self._drive(tid)

    def _chunk_for(self, epoch: int) -> int:
        """Epochs the next exchange buys from ``epoch``: the full exchange
        width, or the remainder to final_epoch (the one partial chunk)."""
        k = self.exchange_width
        return min(k, self.final_epoch - epoch) if self.final_epoch else k

    def _quiescent_period_locked(self, tile: _Tile, halo: Halo, c: int) -> int:
        """0 (active) or the period (1/2) at which the chunk about to run
        repeats a recorded input.  Determinism is the whole proof: the
        chunk output is a pure function of (state, halo, chunk length), so
        an input seen before has an output already in hand.  Halo equality
        is checked FIRST (O(perimeter)) so active tiles — whose boundary
        almost surely moved — never pay the O(tile) state compare.  Caller
        holds the lock."""
        if not self.sparse_cluster or self.engine in ("actor", "actor-native"):
            # Actor engines are stateful (per-cell histories advance with
            # every step); skipping their drive would desynchronize them.
            return 0
        ins = tile.inputs
        p1 = (
            len(ins) >= 1
            and tile.last_ring is not None
            and c == ins[0][2]
            and halos_equal(halo, ins[0][1])
        )
        p2 = (
            len(ins) >= 2
            and tile.prev_ring is not None
            and c == ins[1][2]
            and halos_equal(halo, ins[1][1])
        )
        if not (p1 or p2):
            # The boundary moved: the common active case, and free — no
            # state compare, and any probe backoff is moot.
            tile.q_probe_wait = 0
            return 0
        # Identity fast paths: a tile already quiescent holds the SAME
        # array object its matching input recorded, so steady-state skips
        # cost O(perimeter) only.
        if p1 and tile.arr is ins[0][0]:
            return 1
        if p2 and tile.arr is ins[1][0]:
            return 2
        # The O(tile) probes, under adaptive backoff: a tile whose halo is
        # static but whose INTERIOR is active would otherwise pay up to two
        # full memcmps per chunk — exactly the dilute pattern this tier
        # targets.  A failed probe doubles the wait (capped); the only cost
        # of waiting is entering quiescence a few chunks late.
        if tile.q_probe_wait > 0:
            tile.q_probe_wait -= 1
            return 0
        if p1 and np.array_equal(tile.arr, ins[0][0]):
            tile.q_probe_backoff = 0
            return 1
        if p2 and np.array_equal(tile.arr, ins[1][0]):
            tile.q_probe_backoff = 0
            return 2
        tile.q_probe_backoff = min(8, max(1, 2 * tile.q_probe_backoff))
        tile.q_probe_wait = tile.q_probe_backoff
        return 0

    def _step_tile(self, tid: TileId, epoch: int, halo: Halo) -> bool:
        """One chunk (1..exchange_width epochs) of one tile.  Compute happens
        under the lock; ring and state sends happen after releasing it so two
        workers never hold their locks while writing into each other's
        sockets.

        Quiescence tier (sparse_cluster): a chunk whose (state, halo, len)
        input matches the previous chunk's is a fixed point — its output IS
        the current state; one matching the chunk before that is period-2 —
        its output IS the previous state.  Either way the compute is
        skipped, the ring publish collapses to an O(1)-byte same-ring
        marker, and the PROGRESS ping is suppressed except at cadence/
        digest-due epochs and on the quiesce transition itself.  Epochs
        still advance through the normal epoch-tagged protocol, so a
        changed neighboring ring simply fails the halo-equality test on
        the next chunk and the tile computes again — the wake needs no
        message of its own and can never run a wrong-state epoch."""
        with self._lock:
            tile = self.tiles.get(tid)
            c = self._chunk_for(epoch)
            if (
                tile is None
                or epoch != tile.epoch  # stale/duplicate completion: drop
                or self.paused
                or c <= 0
                or self.target < epoch + c
                # Frozen for migration: refuse the chunk; an abort/expiry
                # re-drives and the halo reassembles from stored rings.
                or (
                    tile.frozen_until
                    and time.monotonic() < tile.frozen_until
                )
            ):
                if tile is not None and epoch == tile.epoch:
                    tile.awaiting_since = None  # paused/short target: clear latch
                return False
            period = self._quiescent_period_locked(tile, halo, c)
            if period:
                prev_state = tile.arr
                new_arr = tile.arr if period == 1 else tile.inputs[0][0]
                reuse = tile.last_ring if period == 1 else tile.prev_ring
                ring, same_as = reuse
                entered = tile.q_period == 0
                tile.q_period = period
                tile.q_skipped += 1
                tile.arr = new_arr
            else:
                prev_state = tile.arr
                padded = halo.pad(prev_state)
                with self.tracer.span(
                    "backend.step", parent=self._trace_ctx,
                    node=self.name or "backend", tile=str(tid), epoch=epoch,
                    chunk=c,
                ):
                    if self.engine in ("actor", "actor-native"):
                        # Actor engines exchange per-epoch (the frontend
                        # rejects them when exchange_width > 1), so c == 1.
                        tile.arr = self._actor_engines[tid].step(padded)
                    else:
                        tile.arr = self._step_chunk(
                            padded, c, self.exchange_width
                        )
                tile.q_period = 0
                ring, same_as = Ring.of(tile.arr, self.exchange_width), None
            if self.sparse_cluster:
                # Record the chunk input for the next quiescence test —
                # references only; compute allocated a fresh array, so the
                # old ones stay valid.  Off, nothing is retained (holding
                # two extra boards per tile is the feature's cost, not a
                # default tax).
                tile.inputs.appendleft((prev_state, halo, c))
            tile.epoch += c
            tile.awaiting_since = None
            tile.retries = 0
            tile.retry_delay = self.retry_s  # backoff resets on success
            # Ring history rotation HERE, under the same lock that orders
            # chunk completion: rotating in _publish_ring (outside the
            # lock) would let two threads publishing consecutive chunks
            # swap last/prev — and a later period-2 skip would then marker
            # the wrong phase's ring.
            tile.prev_ring = tile.last_ring
            tile.last_ring = (ring, tile.epoch)
            # Snapshot (arr, epoch) while still holding the lock: the sends
            # below run unlocked, and a concurrent kick may step the tile
            # again in between — publishing from the live tile there would
            # pair one chunk's data with another's epoch label.
            arr, epoch_now = tile.arr, tile.epoch
        if period:
            self._m_skipped_chunks.inc()
            if entered:
                with self.tracer.span(
                    "tile.quiesce", parent=self._trace_ctx,
                    node=self.name or "backend", tile=str(tid),
                    epoch=epoch_now, period=period,
                ):
                    pass
            self._publish_ring(
                tid, arr, epoch_now, ring=ring, same_as=same_as,
                ping=entered or self._quiescent_ping_due(epoch_now),
            )
        else:
            self._publish_ring(tid, arr, epoch_now, ring=ring)
        self._report_state(tid, arr, epoch_now)
        return True

    def _quiescent_ping_due(self, epoch: int) -> bool:
        """Epochs at which even a quiescent tile must ping: every cadence
        the frontend keys bookkeeping to (checkpoint completion gates,
        prune floor advance, render/metrics lag accounting, the final
        epoch) plus digest-due certificates."""
        if epoch == self.final_epoch:
            return True
        for every in (
            self.checkpoint_every, self.metrics_every, self.render_every
        ):
            if every and epoch % every == 0:
                return True
        return self._digest_due(epoch)

    def _owner_rings_locked(self, tid: TileId) -> Tuple[List[str], Dict[str, set]]:
        """For one publishing tile: the distinct remote owners of its 8
        neighbors, plus — per remote owner — the set of ALL local tiles
        bordering that owner (the batch-seal expectation).  Served from a
        cache invalidated on OWNERS/DEPLOY/tile changes — the map is
        invariant between rewirings, and the publish path runs once per
        tile per chunk under the worker lock.  Caller holds the lock."""
        if self.layout is None:
            return [], {}
        if self._owner_map is None:
            by_tile: Dict[TileId, List[str]] = {}
            expect: Dict[str, set] = {}
            for t in self.tiles:
                remote = {
                    self.owners[ntile][0]
                    for ntile in self.layout.neighbors(t).values()
                    if ntile in self.owners
                    and self.owners[ntile][0] != self.name
                }
                by_tile[t] = sorted(remote)
                for owner in remote:
                    expect.setdefault(owner, set()).add(t)
            self._owner_map = (by_tile, expect)
        by_tile, expect = self._owner_map
        return by_tile.get(tid, []), expect

    def _publish_ring(
        self,
        tid: TileId,
        arr: np.ndarray,
        epoch: int,
        *,
        ring: Optional[Ring] = None,
        same_as: Optional[int] = None,
        ping: bool = True,
    ) -> None:
        """Store our ring locally (answers our own and co-located pulls) and
        queue it for each distinct remote owner among the tile's 8 neighbors
        — the direct neighbor-to-neighbor data plane.  Takes an (arr, epoch)
        snapshot captured under the worker lock, never the live tile.

        Hot-path shape: the ring is encoded ONCE (bit-packed for binary
        rules when ring_pack is on), the owner set and payload accounting
        are computed once per publish, and the per-owner loop only enqueues
        onto async sender lanes — no socket work, no re-encoding, no
        blocking on a slow peer.

        Quiescent publish (``same_as`` set): ``ring`` is the reused ring
        object published at epoch ``same_as``, re-stored locally (a shared
        reference, no copy) while remote owners receive an O(1)-byte
        ``same_as`` marker instead of payload — the receiver resolves it
        against its own store.  ``ping=False`` additionally suppresses the
        per-chunk PROGRESS ping (cadence/digest epochs keep it).

        ``ring=None`` is the deploy-time announce: the tile is not yet
        being driven (single-threaded for it), so the ring is computed —
        and the last/prev ring history rotated — right here.  Step-loop
        publishes instead pass the ring rotated inside ``_step_tile``'s
        locked section, where chunk completion order is serialized; a
        rotation here would race a concurrent publish of the next chunk
        and could invert last/prev under a later period-2 skip."""
        marker = same_as is not None
        if ring is None:
            ring = Ring.of(arr, self.exchange_width)
            with self._lock:
                tile = self.tiles.get(tid)
                if tile is not None:
                    tile.prev_ring = tile.last_ring
                    tile.last_ring = (ring, epoch)
        if self.store is not None:
            self.store.push_ring(tid, epoch, ring)
        with self._lock:
            remote_owners, expect = self._owner_rings_locked(tid)
        if not remote_owners:
            if ping:
                self._progress_ping(tid, epoch, arr)
            return
        pack = self.ring_pack and self.rule is not None and self.rule.is_binary
        # Wire-cost accounting (the Casper data-movement signal at the
        # cluster layer): dense cell bytes AND actual encoded wire bytes
        # per remote copy — their ratio is the packing win.  The raw
        # unbatched baseline ships the legacy per-field message, so its
        # wire bytes ARE the dense bytes and nothing needs encoding — the
        # A/B baseline must not pay a concatenate+copy it never sends.
        # A quiescence marker ships no payload at all: dense bytes still
        # count (the logical exchange happened), wire bytes count zero.
        if marker:
            enc, wire = None, 0
            self._m_same_markers.inc(len(remote_owners))
        elif pack or self.ring_batch:
            enc = encode_ring(ring, pack)
            wire = ring_entry_nbytes(enc)
        else:
            enc, wire = None, ring.nbytes
        self._m_ring_bytes.inc(ring.nbytes * len(remote_owners))
        self._m_packed_bytes.inc(wire * len(remote_owners))
        with self.tracer.span(
            "halo.send", parent=self._trace_ctx,
            node=self.name or "backend", tile=str(tid), epoch=epoch,
            peers=len(remote_owners), bytes=wire * len(remote_owners),
        ):
            if self.ring_batch:
                entry = (
                    {"tile": list(tid), "epoch": epoch, "same_as": same_as}
                    if marker
                    else {"tile": list(tid), "epoch": epoch, "ring": enc}
                )
                for owner in remote_owners:
                    s = self._sender(owner)
                    if s is not None:  # departed between snapshot and here
                        s.enqueue_ring(entry, expect.get(owner, ()))
            else:
                # Frame-per-ring mode (the reference's wire shape, kept for
                # A/B measurement): still async, still encoded at most once.
                if marker:
                    msg = {
                        "type": P.PEER_RING, "tile": list(tid),
                        "epoch": epoch, "same_as": same_as,
                    }
                elif pack:
                    msg = {
                        "type": P.PEER_RING, "tile": list(tid),
                        "epoch": epoch, "ring": enc,
                    }
                else:
                    msg = _ring_msg(tid, epoch, ring)
                for owner in remote_owners:
                    self._send_peer(owner, msg)
        if ping:
            self._progress_ping(tid, epoch, arr)

    def _digest_due(self, epoch: int) -> bool:
        """Epochs whose PROGRESS ping carries the tile's digest lanes:
        metrics and checkpoint cadence crossings plus the final epoch —
        exactly the points the frontend certifies or makes durable."""
        if not self.obs_digest or epoch <= 0:
            return False
        if epoch == self.final_epoch:
            return True
        if self.checkpoint_every and epoch % self.checkpoint_every == 0:
            return True
        return bool(self.metrics_every and epoch % self.metrics_every == 0)

    def _progress_ping(
        self, tid: TileId, epoch: int, arr: Optional[np.ndarray] = None
    ) -> None:
        """Control-plane progress ping (no arrays): feeds the frontend's
        prune floor, stuck detection, and lag accounting.  At digest-due
        epochs it additionally carries the tile's 64-bit fingerprint lanes
        (~8 bytes — the mergeable per-tile form of the digest plane), so
        the frontend certifies whole-cluster state in O(tiles) bytes.

        Quiescence tier: the ping additionally reports the tile's live
        period (``q``) and the chunks skipped since the last ping
        (``skipped``) — the frontend folds the deltas into
        ``gol_tiles_skipped_total`` and tracks the quiescent set for
        ``/healthz``."""
        msg = {"type": P.PROGRESS, "tile": list(tid), "epoch": epoch}
        if self.sparse_cluster:
            with self._lock:
                tile = self.tiles.get(tid)
                if tile is not None:
                    msg["q"] = tile.q_period
                    if tile.q_skipped:
                        msg["skipped"] = tile.q_skipped
                        tile.q_skipped = 0
        if arr is not None and self._digest_due(epoch):
            from akka_game_of_life_tpu.ops import digest as odigest

            with self._lock:
                origin = self.origins.get(tid, (0, 0))
                width = (
                    self.layout.board_shape[1] if self.layout is not None
                    else arr.shape[1]
                )
            lanes = odigest.digest_dense_np(arr, origin, width)
            msg["digest"] = [int(lanes[0]), int(lanes[1])]
        try:
            self.channel.send(msg)
        except OSError:
            pass

    def _report_state(self, tid: TileId, arr: np.ndarray, epoch: int) -> None:
        """Report tile state at cadence boundaries, shipping only what each
        reason needs — never the raw full tile (VERDICT.md weak #5):
        checkpoint/final ride bit-packed (8 cells/byte), render ships the
        frontend's strided sample, metrics ships a single population count.
        Takes an (arr, epoch) snapshot captured under the worker lock."""
        reasons = []
        e = epoch
        if e == self.final_epoch:
            reasons.append("final")
        if self.checkpoint_every and e > 0 and e % self.checkpoint_every == 0:
            reasons.append("checkpoint")
        if self.render_every and e % self.render_every == 0:
            reasons.append("render")
        if self.metrics_every and e % self.metrics_every == 0:
            reasons.append("metrics")
        if not reasons:
            return
        msg = {
            "type": P.TILE_STATE,
            "tile": list(tid),
            "epoch": e,
            "reasons": reasons,
        }
        if "final" in reasons or "checkpoint" in reasons:
            msg["state"] = pack_tile(arr)
        if "render" in reasons:
            sy, sx = self.render_strides
            with self._lock:
                oy, ox = self.origins.get(tid, (0, 0))
            # Phase-align to the tile origin so the union over tiles is the
            # canonical full-board strided probe (cell (0,0) always shown).
            msg["sample"] = arr[(-oy) % sy :: sy, (-ox) % sx :: sx]
            msg["scaled_origin"] = [
                (oy + sy - 1) // sy,
                (ox + sx - 1) // sx,
            ]
            if self.probe_window is not None:
                # Exact cells of this tile's intersection with the probe
                # window, origin given window-relative; the intersections
                # over all reporting tiles tile the window exactly.
                y0, y1, x0, x1 = self.probe_window
                h, w = arr.shape
                gy0, gy1 = max(y0, oy), min(y1, oy + h)
                gx0, gx1 = max(x0, ox), min(x1, ox + w)
                if gy0 < gy1 and gx0 < gx1:
                    msg["window"] = arr[
                        gy0 - oy : gy1 - oy, gx0 - ox : gx1 - ox
                    ]
                    msg["window_origin"] = [gy0 - y0, gx0 - x0]
        if "metrics" in reasons:
            msg["population"] = int((arr == 1).sum())
        try:
            self.channel.send(msg)
        except OSError:
            pass


_SPAN_FORWARD_INTERVAL_S = 1.0
_SPAN_FORWARD_PENDING_CAP = 8192

# How long a SIGTERM'd CLI worker keeps serving the migration protocol
# waiting for its drain to complete before leaving abruptly anyway.
_DRAIN_TIMEOUT_S = 30.0


def _start_span_forwarding(worker: BackendWorker, tracer) -> None:
    """Batch this process's finished spans to the frontend (P.SPANS) so its
    --trace-file / /trace is the cluster-wide causal document.

    Only the multi-process CLI role forwards — the in-process harness
    shares one tracer with the frontend, and forwarding there would
    duplicate every span.  The pending queue is bounded (drop-oldest): a
    frontend that stops draining must not grow worker memory, and trace
    loss under backpressure is the same drop-oldest contract the tracer's
    own ring has."""
    from collections import deque

    # Same drop-oldest idiom as the tracer ring and the flight recorder.
    pending: deque = deque(maxlen=_SPAN_FORWARD_PENDING_CAP)
    lock = threading.Lock()

    def sink(d: dict) -> None:
        with lock:
            pending.append(d)

    tracer.add_sink(sink)

    def flush() -> None:
        with lock:
            batch = list(pending)
            pending.clear()
        if batch:
            worker.channel.send({"type": P.SPANS, "spans": batch})

    def flush_loop() -> None:
        while not worker._stop.wait(_SPAN_FORWARD_INTERVAL_S):
            try:
                flush()
            except OSError:
                return

    # Final drain before the control channel closes on an orderly exit, so
    # the frontend's trace file carries this worker's last spans (the tail
    # of the run, and — on a SHUTDOWN right after a fault — the recovery).
    worker._pre_stop_hooks.append(flush)
    threading.Thread(
        target=flush_loop, daemon=True, name="span-forward"
    ).start()


def run_backend(
    host: str,
    port: int,
    name: Optional[str] = None,
    engine: str = "jax",
    pallas: Optional[str] = None,
    metrics_file: Optional[str] = None,
    metrics_port: int = 0,
    log_events: Optional[str] = None,
    trace_file: Optional[str] = None,
    flight_dir: str = "artifacts",
    net_chaos=None,
) -> int:
    """CLI worker entry.  The worker's data-plane counters (peer sends/
    receives/retries, heartbeats, ring bytes) live in THIS process's
    registry — the frontend's /metrics is a different process — so the
    backend role carries its own exposition: ``metrics_file`` is rewritten
    every few seconds and on exit (the shared MetricsDumper policy),
    ``metrics_port`` serves live /metrics + /healthz + /trace,
    ``log_events`` appends worker-labeled JSONL, ``trace_file`` exports the
    worker's span buffer on exit (same trace ids as the frontend's —
    mergeable), and ``flight_dir`` receives the crash dumps.  ``net_chaos``
    (a :class:`runtime.config.NetworkChaosConfig`) arms this worker's wire
    chaos — same seed/schedule on every role for a coherent drill."""
    from akka_game_of_life_tpu.obs import (
        EventLog,
        MetricsDumper,
        MetricsServer,
        get_registry,
        get_tracer,
    )

    registry = get_registry()
    tracer = get_tracer()
    chaos = (
        NetworkChaos(net_chaos, registry=registry, tracer=tracer)
        if net_chaos is not None and net_chaos.enabled
        else None
    )
    worker = BackendWorker(
        host, port, name=name, engine=engine, pallas=pallas,
        registry=registry, tracer=tracer, netchaos=chaos,
    )
    worker.connect()
    node = worker.name or "backend"
    tracer.node = node  # nodeless spans attribute to this worker
    tracer.flight.configure(directory=flight_dir, node=node)
    events = EventLog(log_events, node=node, recorder=tracer.flight)
    events.emit("backend_joined", frontend=f"{host}:{port}", engine=engine)
    # Program ledger: storm alerts fire through this worker's event log and
    # flight recorder; profiler captures land beside the crash dumps.
    from akka_game_of_life_tpu.obs.programs import get_programs

    worker.profile_dir = flight_dir
    get_programs().configure(
        node=node, events=events, flight=tracer.flight, metrics=registry
    )
    server = None
    if metrics_port:
        server = MetricsServer(
            registry,
            port=metrics_port,
            health=lambda: {
                "ok": not worker._stop.is_set(),
                "tiles": len(worker.tiles),
                "target_epoch": worker.target,
            },
            tracer=tracer,
        )
        print(f"metrics on :{server.port}/metrics (+/healthz,/trace)", flush=True)
    dumper = MetricsDumper(registry, metrics_file) if metrics_file else None
    if dumper is not None:
        dumper.start_thread(worker._stop)
    _start_span_forwarding(worker, tracer)
    print(f"backend {worker.name} joined {host}:{port}", flush=True)
    try:
        return worker.run()
    except KeyboardInterrupt:
        # Graceful operator stop, in two tiers.  First choice: DRAIN — ask
        # the frontend to live-migrate every hosted tile off this worker
        # (digest-certified, zero lost epochs, no node-loss redeploy), keep
        # serving the migration protocol until DRAIN_COMPLETE releases us,
        # and leave rc=0.  The wait is bounded (stop_after) and a second
        # signal skips straight to the abrupt tier.  Fallback (no tiles,
        # refused drain, or timeout): GOODBYE — a deliberate leave the
        # frontend recovers with an immediate checkpoint redeploy instead
        # of waiting out the heartbeat timeout.  Masked so a second signal
        # cannot abort the GOODBYE/close half-way.
        from akka_game_of_life_tpu.runtime.signals import (
            mask_interrupts,
            stop_after,
        )

        if worker.request_drain():
            print(
                f"backend {worker.name} draining: handing "
                f"{len(worker.tiles)} tile(s) back",
                flush=True,
            )
            try:
                with stop_after(_DRAIN_TIMEOUT_S, worker.stop):
                    worker.run()
            except KeyboardInterrupt:
                pass  # second signal: give up on the drain, leave now
        with mask_interrupts():
            worker.stop()
        if worker.stopped_reason == "drained":
            print(f"backend {worker.name} drained; leaving", flush=True)
            return 0
        if worker.stopped_reason == "shutdown":
            # The run finished while we were draining: the frontend's
            # clean cluster SHUTDOWN reached us before DRAIN_COMPLETE
            # could (the planner stops once the run is done).  Nothing
            # was lost and nothing redeployed — a clean exit, same as
            # every other worker's.
            print(f"backend {worker.name} shut down mid-drain; leaving", flush=True)
            return 0
        return 130
    finally:
        if dumper is not None:
            dumper.final()
        if trace_file:
            try:
                tracer.write(trace_file)
            except OSError as e:
                print(f"trace-file write failed: {e}", flush=True)
        if server is not None:
            server.close()
        events.emit("backend_stopped", reason=worker.stopped_reason)
        events.close()
