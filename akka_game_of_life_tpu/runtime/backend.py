"""The backend worker — the ``RunBackend`` role, upgraded from container to
shard engine.

The reference's backend is deliberately empty: it starts an ActorSystem,
joins the cluster, and hosts whatever cells the frontend deploys onto it
(``Run.scala:56-65``).  This worker keeps that shape — it owns nothing until
the frontend DEPLOYs tiles — but the deployed unit is a whole grid tile
advanced by a stencil engine:

- ``engine="numpy"``: host stepping, the portable/parity path;
- ``engine="jax"``: jitted stepping on the worker's local accelerator (the
  TPU path; within a multi-device worker the tile itself is mesh-sharded by
  :mod:`akka_game_of_life_tpu.parallel` — ICI inside, control plane outside);
- ``engine="actor"``: the per-cell actor engine
  (:mod:`akka_game_of_life_tpu.runtime.actor_engine`) — the reference's own
  architecture, swappable at role config (BASELINE config 1).

Per-epoch cycle per tile (the ``CellActor``/gatherer loop collapsed):
PULL halo(E) → (queued at the frontend until all 8 neighbor rings at E exist)
→ HALO reply → step to E+1 → push RING(E+1) → PULL halo(E+1)...  A pending
pull is re-sent after ``retry_s`` (the gatherer's 1 s Retry timer,
``NextStateCellGathererActor.scala:28``).  Tiles lag and catch up
independently — there is no global barrier, matching the reference's
history-buffered asynchrony (``CellActor.scala:41-47``)."""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.ops.npkernel import step_padded_np
from akka_game_of_life_tpu.ops.rules import Rule, resolve_rule
from akka_game_of_life_tpu.runtime import protocol as P
from akka_game_of_life_tpu.runtime.boundary import Halo
from akka_game_of_life_tpu.runtime.tiles import Ring, TileId
from akka_game_of_life_tpu.runtime.wire import Channel


class _Tile:
    def __init__(self, arr: np.ndarray, epoch: int) -> None:
        self.arr = arr
        self.epoch = epoch
        self.awaiting_since: Optional[float] = None  # the waitingForNewState latch
        self.retries = 0


def _jax_engine(rule: Rule) -> Callable[[np.ndarray], np.ndarray]:
    import jax.numpy as jnp

    from akka_game_of_life_tpu.ops.stencil import step_fn_padded

    step = step_fn_padded(rule)

    def run(padded: np.ndarray) -> np.ndarray:
        return np.asarray(step(jnp.asarray(padded)))

    return run


class BackendWorker:
    """One worker process/thread: joins, hosts tiles, steps them."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        name: Optional[str] = None,
        engine: str = "jax",
        retry_s: float = 1.0,
        max_pull_retries: int = 10,
        crash_hook: Optional[Callable[[], None]] = None,
    ) -> None:
        if engine not in ("numpy", "jax", "actor", "actor-native"):
            raise ValueError(
                f"unknown engine {engine!r}; use numpy, jax, actor, or actor-native"
            )
        if engine == "actor-native":
            from akka_game_of_life_tpu.native import available, load_error

            if not available():
                raise RuntimeError(f"actor-native engine unavailable: {load_error()}")
        self.host = host
        self.port = port
        self.name = name
        self.engine = engine
        self.retry_s = retry_s
        self.max_pull_retries = max_pull_retries
        # DoCrashMsg → throw (CellActor.scala:95-96): default is an abrupt
        # process death; in-thread harnesses override to simulate it.
        self.crash_hook = crash_hook or (lambda: os._exit(42))

        self.tiles: Dict[TileId, _Tile] = {}
        self.rule: Optional[Rule] = None
        self.target = 0
        self.final_epoch = 0
        self.render_every = 0
        self.checkpoint_every = 0
        self.metrics_every = 0
        self.paused = False
        self.channel: Optional[Channel] = None
        self._step_padded: Optional[Callable[[np.ndarray], np.ndarray]] = None
        self._actor_engines: Dict[TileId, object] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self.stopped_reason: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def connect(self) -> None:
        sock = socket.create_connection((self.host, self.port), timeout=10)
        sock.settimeout(None)
        self.channel = Channel(sock)
        self.channel.send({"type": P.REGISTER, "name": self.name})
        welcome = self.channel.recv()
        if not welcome or welcome.get("type") != P.WELCOME:
            raise ConnectionError("frontend did not welcome us")
        self.name = welcome["name"]
        heartbeat_s = float(welcome.get("heartbeat_s", 0.5))
        # Retry policy is cluster config, owned by the frontend
        # (SimulationConfig.max_pull_retries); the constructor value is only
        # the standalone/test default.
        if "max_pull_retries" in welcome:
            self.max_pull_retries = int(welcome["max_pull_retries"])
        threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_s,), daemon=True
        ).start()
        threading.Thread(target=self._retry_loop, daemon=True).start()

    def run(self) -> int:
        """Blocking serve loop; returns when shut down or disconnected."""
        if self.channel is None:
            self.connect()
        try:
            while not self._stop.is_set():
                msg = self.channel.recv()
                if msg is None:
                    self.stopped_reason = self.stopped_reason or "disconnected"
                    break
                self._dispatch(msg)
        except OSError:
            self.stopped_reason = self.stopped_reason or "connection error"
        finally:
            self._stop.set()
        return 0 if self.stopped_reason == "shutdown" else 1

    def stop(self) -> None:
        self._stop.set()
        if self.channel is not None:
            try:
                # Graceful leave (cluster down): distinguishable from a crash.
                self.channel.send({"type": P.GOODBYE})
            except OSError:
                pass
            self.channel.close()

    # -- helper threads ------------------------------------------------------

    def _heartbeat_loop(self, interval: float) -> None:
        while not self._stop.is_set():
            time.sleep(interval)
            try:
                self.channel.send({"type": P.HEARTBEAT})
            except OSError:
                return

    def _retry_loop(self) -> None:
        """The gatherer's Retry timer: re-pull stale halo requests.

        After ``max_pull_retries`` unanswered re-pulls the worker escalates
        with GATHER_FAILED — the reference's gatherer gives up after 2 ask
        rounds and fires ``FailedToGatherInfoMsg`` so its parent repairs the
        neighborhood (``NextStateCellGathererActor.scala:49-58``,
        ``CellActor.scala:92-94``).  Like the reference, the tile keeps its
        state and keeps retrying; the frontend decides whether a blocking
        neighbor is genuinely stuck and needs redeployment."""
        while not self._stop.is_set():
            time.sleep(self.retry_s / 4)
            now = time.monotonic()
            failed = []
            with self._lock:
                if self.paused:
                    continue
                stale = [
                    (tid, t)
                    for tid, t in self.tiles.items()
                    if t.awaiting_since is not None
                    and now - t.awaiting_since > self.retry_s
                ]
                for tid, t in stale:
                    t.retries += 1
                    if t.retries > self.max_pull_retries:
                        t.retries = 0  # re-arm: escalate again if still stuck
                        failed.append((tid, t.epoch))
                    t.awaiting_since = now
                    self._send_pull(tid, t)
            for tid, epoch in failed:
                try:
                    self.channel.send(
                        {"type": P.GATHER_FAILED, "tile": list(tid), "epoch": epoch}
                    )
                except OSError:
                    pass

    # -- dispatch ------------------------------------------------------------

    def _dispatch(self, msg: dict) -> None:
        kind = msg.get("type")
        if kind == P.DEPLOY:
            self._on_deploy(msg)
        elif kind == P.TICK:
            with self._lock:
                self.target = int(msg["target"])
                self._kick()
        elif kind == P.HALO:
            self._on_halo(msg)
        elif kind == P.PAUSE:
            with self._lock:
                self.paused = True
        elif kind == P.RESUME:
            with self._lock:
                self.paused = False
                self._kick()
        elif kind == P.CRASH:
            self.crash_hook()
        elif kind == P.CRASH_TILE:
            self._on_crash_tile(tuple(msg["tile"]))
        elif kind == P.SHUTDOWN:
            self.stopped_reason = "shutdown"
            self._stop.set()
            self.channel.close()

    def _on_deploy(self, msg: dict) -> None:
        with self._lock:
            rule = resolve_rule(msg["rule"])
            if self.rule != rule:
                self.rule = rule
                if self.engine == "jax":
                    self._step_padded = _jax_engine(rule)
                elif self.engine == "numpy":
                    self._step_padded = lambda padded: step_padded_np(padded, rule)
                # engine == "actor": stateful per-tile engines, built below
            self.target = int(msg["target"])
            self.final_epoch = int(msg["final_epoch"])
            self.render_every = int(msg.get("render_every", 0))
            self.checkpoint_every = int(msg.get("checkpoint_every", 0))
            self.metrics_every = int(msg.get("metrics_every", 0))
            for spec in msg["tiles"]:
                tid: TileId = tuple(spec["id"])
                tile = _Tile(np.asarray(spec["array"]), int(spec["epoch"]))
                self.tiles[tid] = tile
                if self.engine == "actor":
                    # A (re)deploy is a supervision restart: fresh actors,
                    # histories reseeded from the deployed array.
                    from akka_game_of_life_tpu.runtime.actor_engine import (
                        ActorTileEngine,
                    )

                    self._actor_engines[tid] = ActorTileEngine(rule)
                elif self.engine == "actor-native":
                    from akka_game_of_life_tpu.native.engine import (
                        NativeActorTileEngine,
                    )

                    self._actor_engines[tid] = NativeActorTileEngine(rule)
                # Announce our boundary at the deployed epoch so neighbors
                # can assemble their halos (History seeding,
                # CellActor.scala:34).
                self._send_ring(tid, tile)
                self._maybe_send_state(tid, tile)
            self._kick()

    def _on_halo(self, msg: dict) -> None:
        tid: TileId = tuple(msg["tile"])
        epoch = int(msg["epoch"])
        with self._lock:
            tile = self.tiles.get(tid)
            if (
                tile is None
                or epoch != tile.epoch  # stale/duplicate reply: drop
                or self.paused
                or tile.epoch >= self.target
            ):
                if tile is not None and epoch == tile.epoch:
                    tile.awaiting_since = None  # paused: clear latch
                return
            halo = Halo.from_wire(msg["halo"])
            padded = halo.pad(tile.arr)
            if self.engine in ("actor", "actor-native"):
                tile.arr = self._actor_engines[tid].step(padded)
            else:
                tile.arr = self._step_padded(padded)
            tile.epoch += 1
            tile.awaiting_since = None
            tile.retries = 0
            self._send_ring(tid, tile)
            self._maybe_send_state(tid, tile)
            if tile.epoch < self.target:
                self._send_pull(tid, tile)

    def _on_crash_tile(self, tid: TileId) -> None:
        """Supervision-restart analog: the tile's in-memory state is lost;
        ask the parent to redeploy (postRestart → SendMeMyNeighbours,
        CellActor.scala:21-25)."""
        with self._lock:
            if tid in self.tiles:
                del self.tiles[tid]
            self._actor_engines.pop(tid, None)
        try:
            self.channel.send({"type": P.REDEPLOY_REQUEST, "tile": list(tid)})
        except OSError:
            pass

    # -- stepping plumbing ---------------------------------------------------

    def _kick(self) -> None:
        """Start pulls for every tile that is behind and not already waiting
        (scheduleTransitionToNextepochIfNeeded, CellActor.scala:41-47)."""
        if self.paused:
            return
        for tid, tile in self.tiles.items():
            if tile.epoch < self.target and tile.awaiting_since is None:
                self._send_pull(tid, tile)

    def _send_pull(self, tid: TileId, tile: _Tile) -> None:
        tile.awaiting_since = time.monotonic()
        try:
            self.channel.send(
                {"type": P.PULL, "tile": list(tid), "epoch": tile.epoch}
            )
        except OSError:
            pass

    def _send_ring(self, tid: TileId, tile: _Tile) -> None:
        ring = Ring.of(tile.arr)
        try:
            self.channel.send(
                {
                    "type": P.RING,
                    "tile": list(tid),
                    "epoch": tile.epoch,
                    "top": ring.top,
                    "bottom": ring.bottom,
                    "left": ring.left,
                    "right": ring.right,
                    "corners": ring.corners,
                }
            )
        except OSError:
            pass

    def _maybe_send_state(self, tid: TileId, tile: _Tile) -> None:
        reasons = []
        e = tile.epoch
        if e == self.final_epoch:
            reasons.append("final")
        if self.checkpoint_every and e > 0 and e % self.checkpoint_every == 0:
            reasons.append("checkpoint")
        if self.render_every and e % self.render_every == 0:
            reasons.append("render")
        if self.metrics_every and e % self.metrics_every == 0:
            reasons.append("metrics")
        if not reasons:
            return
        try:
            self.channel.send(
                {
                    "type": P.TILE_STATE,
                    "tile": list(tid),
                    "epoch": e,
                    "array": tile.arr,
                    "reasons": reasons,
                }
            )
        except OSError:
            pass


def run_backend(
    host: str, port: int, name: Optional[str] = None, engine: str = "jax"
) -> int:
    worker = BackendWorker(host, port, name=name, engine=engine)
    worker.connect()
    print(f"backend {worker.name} joined {host}:{port}", flush=True)
    return worker.run()
