"""Rendering & observability — the ``LoggerActor`` capability, done right.

The reference's logger collects per-cell messages and renders an epoch's
board once `x*y` messages have arrived (``LoggerActor.scala:27-44``) — but
slices them by *arrival order*, so rows come out scrambled, and its
"complete" check fires early because of the board off-by-one (SURVEY.md §2
bugs 2-3).  This renderer assembles frames by position, only marks an epoch
complete when every tile has reported, and stride-samples huge boards (a
65536² frame cannot be dumped wholesale — SURVEY.md §7 hard part e).

It also carries the metrics the reference entirely lacks (SURVEY.md §5):
cell-updates/sec, step latency, population.
"""

from __future__ import annotations

import dataclasses
import sys
import time
from collections import deque
from typing import Deque, Dict, IO, Optional, Tuple

import numpy as np

GLYPHS = ".#ox*+=%"  # state 0..7 glyphs; >7 rendered as '?'


def sample_strides(shape: Tuple[int, int], max_cells: int) -> Tuple[int, int]:
    """Strides that sample an (H, W) board down to <= max_cells per side."""
    return (
        max(1, -(-shape[0] // max_cells)),
        max(1, -(-shape[1] // max_cells)),
    )


def ascii_rows(board: np.ndarray) -> str:
    return "\n".join(
        "".join(GLYPHS[int(v)] if int(v) < len(GLYPHS) else "?" for v in row)
        for row in board
    )


def frame_header(shape: Tuple[int, int], strides: Tuple[int, int]) -> str:
    h, w = shape
    sy, sx = strides
    return f"[{h}x{w}" + (f", sampled /{sy}x{sx}" if (sy, sx) != (1, 1) else "") + "]"


def render_ascii(board: np.ndarray, max_cells: int = 128) -> str:
    """Render a board as ASCII rows, stride-sampling to <= max_cells/side.

    Sampling keeps the aspect and phase: cell (0,0) is always shown, matching
    how a strided probe of a torus should behave.
    """
    sy, sx = sample_strides(board.shape, max_cells)
    view = board[::sy, ::sx]
    return frame_header(board.shape, (sy, sx)) + "\n" + ascii_rows(view)


@dataclasses.dataclass
class StepMetrics:
    epoch: int
    seconds: float  # wall time since the previous observation
    epochs: int  # generations covered by that interval
    cells: int  # cell-updates in the interval (board.size * epochs)
    population: int
    # Wall time the observation itself spent (device obs dispatch + host
    # fetches) inside ``seconds`` — the product-vs-bench breakdown: the
    # stepper's own share of the interval is seconds - obs_seconds.
    obs_seconds: float = 0.0
    # 64-bit on-device board digest at this epoch (obs_digest mode), or
    # None — the O(1)-byte state certificate (ops/digest.py).
    digest: Optional[int] = None

    @property
    def updates_per_sec(self) -> float:
        return self.cells / self.seconds if self.seconds > 0 else float("inf")

    @property
    def seconds_per_epoch(self) -> float:
        return self.seconds / self.epochs if self.epochs else 0.0


class BoardObserver:
    """Epoch-synchronized frame sink + metrics counter.

    ``observe(epoch, board)`` renders complete boards; ``observe_tile`` lets
    the distributed control plane feed per-shard tiles and only renders once
    all tiles for an epoch have landed — the reference's complete-epoch
    barrier (``LoggerActor.scala:35``), with correct placement.
    """

    def __init__(
        self,
        *,
        render_every: int = 0,
        render_max_cells: int = 128,
        metrics_every: int = 0,
        out: Optional[IO[str]] = None,
        log_file: Optional[str] = None,
        registry=None,
    ) -> None:
        self.render_every = render_every
        self.render_max_cells = render_max_cells
        self.metrics_every = metrics_every
        # Progress gauges land in the metrics registry on every observed
        # interval (standalone AND cluster paths both funnel through
        # _note_progress) — "what is the steps/s right now" as a scrape
        # instead of a stdout grep.
        if registry is None:
            from akka_game_of_life_tpu.obs import get_registry

            registry = get_registry()
        self._population_gauge = registry.gauge("gol_population")
        self._rate_gauge = registry.gauge("gol_steps_per_second")
        self._own_file = None
        if log_file is not None:
            self._own_file = open(log_file, "a")  # reference appends to info.log
            self.out = self._own_file
        else:
            self.out = out if out is not None else sys.stdout
        self._partial: Dict[int, Dict[Tuple[int, int], np.ndarray]] = {}
        # Epochs complete in increasing order (every tile reports its own
        # epochs in order, and an epoch completes only when the *last* tile
        # reports it), so a single floor suffices to recognize re-reports —
        # no matter how far back a replaying tile rolls.
        self._max_completed: Optional[int] = None
        self._expected_tiles: Optional[int] = None
        # Cluster-scale paths: per-epoch population sums (metrics without
        # shipping any array) and stride-sampled frames (render without
        # shipping whole tiles) — a 65536² board never crosses the wire.
        self._board_shape: Optional[Tuple[int, int]] = None
        self._render_strides: Tuple[int, int] = (1, 1)
        self._pop_partial: Dict[int, Dict[object, int]] = {}
        self._pop_floor: Optional[int] = None
        self._sample_partial: Dict[int, Dict[Tuple[int, int], np.ndarray]] = {}
        self._sample_floor: Optional[int] = None
        self._window_bbox: Optional[Tuple[int, int, int, int]] = None
        self._expected_window_tiles = 0
        self._window_partial: Dict[int, Dict] = {}
        self._window_floor: Optional[int] = None
        self._last_time: Optional[float] = None
        self._last_epoch: Optional[int] = None
        # Bounded, unlike the reference's forever-growing per-epoch map
        # (LoggerActor.scala:27,34).
        self.history: Deque[StepMetrics] = deque(maxlen=1024)
        # Running totals for summary() — the deque is a window, not the run.
        self._total_epochs = 0
        self._total_seconds = 0.0
        self._total_cells = 0
        self._total_obs_seconds = 0.0

    # -- complete-board path (standalone runner) -----------------------------

    def start_clock(self, epoch: int) -> None:
        """Anchor the metrics clock at ``epoch`` if it has not started yet.

        Without an anchor the first cadence crossing only *sets* the clock,
        so the first interval is invisible: totals miss it, and a resumed
        run whose remaining span contains a single crossing observes
        nothing at all (no metrics line, no run summary).  Anchoring at
        advance() entry makes totals span the whole run — including, on a
        TPU, the first chunk's jit compile in the first interval (the
        steady-state per-interval lines are unaffected)."""
        if self._last_time is None:
            self._last_time = time.perf_counter()
            self._last_epoch = epoch

    def _note_progress(
        self,
        epoch: int,
        population: int,
        total_cells: int,
        obs_seconds: float = 0.0,
        digest: Optional[int] = None,
    ) -> None:
        """Advance the metrics clock and emit a metrics line at cadence."""
        now = time.perf_counter()
        if self._last_time is not None and epoch > (self._last_epoch or 0):
            dt = now - self._last_time
            epochs = epoch - self._last_epoch
            m = StepMetrics(
                epoch=epoch,
                seconds=dt,
                epochs=epochs,
                cells=total_cells * epochs,
                population=population,
                obs_seconds=obs_seconds,
                digest=digest,
            )
            self.history.append(m)
            self._total_epochs += m.epochs
            self._total_seconds += m.seconds
            self._total_cells += m.cells
            self._total_obs_seconds += m.obs_seconds
            self._population_gauge.set(m.population)
            if m.seconds > 0:
                self._rate_gauge.set(m.epochs / m.seconds)
            if self.metrics_every and epoch % self.metrics_every == 0:
                # obs = the observation's own share of the interval (device
                # obs dispatch + host fetches): ms/epoch minus obs/epochs is
                # the stepper's true per-epoch cost — the measured breakdown
                # behind any product-vs-bench throughput gap.
                obs = (
                    f" (obs {m.obs_seconds * 1e3:.1f} ms)"
                    if m.obs_seconds > 0
                    else ""
                )
                # The state certificate rides the line it certifies: two
                # runs agree at this epoch iff these 16 hex digits match.
                dig = f" digest={m.digest:016x}" if m.digest is not None else ""
                print(
                    f"epoch {epoch}: pop={m.population} "
                    f"{m.updates_per_sec:.3e} cell-updates/s "
                    f"({m.seconds_per_epoch * 1e3:.2f} ms/epoch)" + obs + dig,
                    file=self.out,
                    flush=True,
                )
        self._last_time = now
        self._last_epoch = epoch

    def observe(
        self, epoch: int, board: np.ndarray, digest: Optional[int] = None
    ) -> None:
        self._note_progress(
            epoch, int((board == 1).sum()), board.size, digest=digest
        )
        if self.render_every and epoch % self.render_every == 0:
            print(f"epoch {epoch}:", file=self.out)
            print(render_ascii(board, self.render_max_cells), file=self.out, flush=True)

    def observe_summary(
        self,
        epoch: int,
        population: int,
        board_shape: Tuple[int, int],
        view: Optional[np.ndarray] = None,
        strides: Tuple[int, int] = (1, 1),
        obs_seconds: float = 0.0,
        digest: Optional[int] = None,
    ) -> None:
        """Device-side observation: the caller computed the population and
        (at render cadence) a stride-sampled view on the accelerator, so only
        a chunk-sum vector and a <=max_cells² probe ever reached the host —
        the standalone analog of the cluster's sampled TILE_STATE path
        (nothing here is O(board)).  ``obs_seconds`` is the caller-measured
        wall cost of that observation (dispatch + fetches), surfaced on the
        metrics line."""
        h, w = board_shape
        self._note_progress(
            epoch, population, h * w, obs_seconds=obs_seconds, digest=digest
        )
        if self.render_every and epoch % self.render_every == 0 and view is not None:
            print(f"epoch {epoch}:", file=self.out)
            print(
                frame_header(board_shape, strides) + "\n" + ascii_rows(view),
                file=self.out,
                flush=True,
            )

    def observe_window(
        self, epoch: int, window: np.ndarray, bbox: Tuple[int, int, int, int]
    ) -> None:
        """An exact-cell probe window (``Simulation.board_window``) at render
        cadence — the at-scale correctness view: e.g. the Gosper-gun region
        of a 65536² run, bytes on the wire where a frame would be 4 GiB."""
        y0, y1, x0, x1 = bbox
        print(
            f"epoch {epoch}: window [{y0}:{y1}, {x0}:{x1}] "
            f"pop={int(np.count_nonzero(window))}\n" + ascii_rows(window),
            file=self.out,
            flush=True,
        )

    # -- tiled path (distributed control plane) ------------------------------

    def expect_tiles(self, n: int) -> None:
        self._expected_tiles = n

    def set_cluster_layout(self, n_tiles: int, board_shape: Tuple[int, int]) -> None:
        """Configure the scale-safe cluster paths (sampled frames +
        population-only metrics)."""
        self._expected_tiles = n_tiles
        self._board_shape = tuple(board_shape)
        self._render_strides = sample_strides(self._board_shape, self.render_max_cells)

    @property
    def render_strides(self) -> Tuple[int, int]:
        """(sy, sx) every worker samples its render tiles with (phase-aligned
        to its origin so the union is the canonical strided probe)."""
        return self._render_strides

    def _complete_epoch(
        self, store: Dict, floor_name: str, expected: int, epoch: int, key, item
    ):
        """The shared per-tile accumulation mechanism behind populations,
        sampled frames, and probe windows: collect items per epoch, and once
        every expected tile reported, advance the monotone completion floor
        (re-reports from replaying tiles are recognized by it), prune stale
        epochs, and hand back the complete dict — else None."""
        floor = getattr(self, floor_name)
        if floor is not None and epoch <= floor:
            return None
        tiles = store.setdefault(epoch, {})
        tiles[key] = item
        if len(tiles) < expected:
            return None
        del store[epoch]
        setattr(self, floor_name, epoch)
        for e in [e for e in store if e <= epoch]:
            del store[e]
        return tiles

    def add_population(self, epoch: int, key, population: int) -> None:
        """One tile's population at a metrics-cadence epoch; emits the
        metrics line when every tile has reported."""
        d = self._complete_epoch(
            self._pop_partial,
            "_pop_floor",
            self._expected_tiles or 0,
            epoch,
            key,
            int(population),
        )
        if d is None:
            return
        h, w = self._board_shape
        self._note_progress(epoch, sum(d.values()), h * w)

    def expect_window(
        self, bbox: Tuple[int, int, int, int], n_tiles: int
    ) -> None:
        """Configure cluster window assembly: ``n_tiles`` workers' tiles
        intersect ``bbox`` and each attaches its exact intersection to its
        render-cadence report."""
        self._window_bbox = tuple(bbox)
        self._expected_window_tiles = n_tiles
        self._window_partial: Dict[int, Dict] = {}
        self._window_floor: Optional[int] = None

    def add_window(
        self, epoch: int, key, origin: Tuple[int, int], block: np.ndarray
    ) -> None:
        """One tile's window intersection (window-relative origin); stitches
        and prints the exact window once every intersecting tile reported."""
        if self._window_bbox is None:
            return
        tiles = self._complete_epoch(
            self._window_partial,
            "_window_floor",
            self._expected_window_tiles,
            epoch,
            key,
            (tuple(origin), np.asarray(block)),
        )
        if tiles is None:
            return
        from akka_game_of_life_tpu.runtime.tiles import stitch

        self.observe_window(epoch, stitch(dict(tiles.values())), self._window_bbox)

    def add_sample(
        self,
        epoch: int,
        key,
        scaled_origin: Tuple[int, int],
        sample: np.ndarray,
    ) -> None:
        """One tile's stride-sampled view at a render-cadence epoch; stitches
        and prints the frame when every tile has reported.  ``key`` is the
        tile's identity (completion is counted by tile, since a tile smaller
        than the stride contributes an empty sample)."""
        tiles = self._complete_epoch(
            self._sample_partial,
            "_sample_floor",
            self._expected_tiles or 0,
            epoch,
            key,
            (tuple(scaled_origin), np.asarray(sample)),
        )
        if tiles is None:
            return
        from akka_game_of_life_tpu.runtime.tiles import stitch

        view = stitch(
            {o: s for o, s in tiles.values() if s.size}  # drop empty slivers
        )
        print(f"epoch {epoch}:", file=self.out)
        print(
            frame_header(self._board_shape, self._render_strides) + "\n"
            + ascii_rows(view),
            file=self.out,
            flush=True,
        )

    def observe_tile(
        self, epoch: int, tile_origin: Tuple[int, int], tile: np.ndarray
    ) -> Optional[np.ndarray]:
        """Feed one shard's tile; returns the assembled board when the epoch
        is complete, else None."""
        if self._expected_tiles is None:
            raise RuntimeError("call expect_tiles(n) before observe_tile")
        if self._max_completed is not None and epoch <= self._max_completed:
            # A replaying tile re-reports epochs already rendered; recreating
            # a partial entry for them would leak (it can never complete).
            return None
        tiles = self._partial.setdefault(epoch, {})
        tiles[tile_origin] = np.asarray(tile)
        if len(tiles) < self._expected_tiles:
            return None
        del self._partial[epoch]
        self._max_completed = epoch
        # Anything still partial at or below the floor can never complete.
        for e in [e for e in self._partial if e <= epoch]:
            del self._partial[e]
        from akka_game_of_life_tpu.runtime.tiles import stitch

        board = stitch(tiles)
        self.observe(epoch, board)
        return board

    def summary(self) -> Optional[dict]:
        """Aggregate run statistics over ALL observed intervals (running
        totals — the bounded history deque is only a window): epochs
        covered, wall seconds, mean rate, last population.  None if no
        intervals were observed."""
        if not self.history:
            return None
        out = {
            "epochs_observed": self._total_epochs,
            "seconds": round(self._total_seconds, 3),
            "cell_updates_per_sec": (
                self._total_cells / self._total_seconds
                if self._total_seconds > 0
                else None
            ),
            "final_population": self.history[-1].population,
        }
        if self._total_obs_seconds > 0:
            # The observation share of the whole run (the breakdown behind
            # any product-vs-bench throughput gap), and the rate the
            # stepper alone sustained outside observation windows.
            out["obs_seconds"] = round(self._total_obs_seconds, 3)
            compute = self._total_seconds - self._total_obs_seconds
            if compute > 0:
                out["stepper_cell_updates_per_sec"] = self._total_cells / compute
        return out

    def close(self) -> None:
        if self._own_file is not None:
            self._own_file.close()
            self._own_file = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
