"""Orbax-backed checkpoint store — async, device-native saves.

The .npz store (:mod:`akka_game_of_life_tpu.runtime.checkpoint`) gathers the
board to host memory and writes synchronously; fine for the control plane's
assembled frames, but the TPU-native path can do better: Orbax saves a
``jax.Array`` directly from device memory — sharded arrays write per-shard
without ever being assembled on one host — and commits in a background
thread so the simulation loop is not blocked on IO (the write overlaps the
next scan chunk).  Same durability contract as the .npz store: atomic
finalization, resumable latest step, bounded retention.

Selected with ``checkpoint_format = "orbax"`` (config or
``--checkpoint-format``); the .npz store stays the default and the two are
interchangeable behind :func:`akka_game_of_life_tpu.runtime.checkpoint.make_store`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import numpy as np

from akka_game_of_life_tpu.runtime.checkpoint import Checkpoint


class OrbaxCheckpointStore:
    """Epoch-stamped checkpoints via ``orbax.checkpoint.CheckpointManager``.

    API-compatible with :class:`CheckpointStore`; additionally accepts
    device-resident (and sharded) ``jax.Array`` boards without host gather.
    """

    def __init__(
        self, directory: str, keep: int = 3, registry=None, tracer=None
    ) -> None:
        import orbax.checkpoint as ocp

        from akka_game_of_life_tpu.runtime.checkpoint import _StoreMetrics

        self.metrics = _StoreMetrics(registry, tracer=tracer)
        self._ocp = ocp
        self.dir = Path(directory).absolute()
        self.dir.mkdir(parents=True, exist_ok=True)
        self.keep = keep
        self._mgr = ocp.CheckpointManager(
            str(self.dir),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep,
                enable_async_checkpointing=True,
            ),
        )

    def save(self, epoch: int, board, rule: str, meta: Optional[dict] = None):
        ocp = self._ocp
        # The timed span is the *dispatch* cost (orbax commits in the
        # background); the save still counts here — wait()/close() surface
        # failures, and counting at dispatch matches the async-npz writer.
        with self.metrics.timed_save():
            self._mgr.save(
                int(epoch),
                args=ocp.args.Composite(
                    state=ocp.args.PyTreeSave({"board": board}),
                    meta=ocp.args.JsonSave({"rule": rule, **(meta or {})}),
                ),
            )
        return self.dir / str(int(epoch))

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def latest_epoch(self) -> Optional[int]:
        self.wait()
        step = self._mgr.latest_step()
        return int(step) if step is not None else None

    def epochs(self):
        """Every durable epoch, sorted (the inspection surface)."""
        self.wait()
        return sorted(int(s) for s in self._mgr.all_steps())

    def load(
        self, epoch: Optional[int] = None, *, keep_packed: bool = False
    ) -> Checkpoint:
        with self.metrics.timed_restore():
            return self._load(epoch, keep_packed=keep_packed)

    def _load(
        self, epoch: Optional[int] = None, *, keep_packed: bool = False
    ) -> Checkpoint:
        ocp = self._ocp
        self.wait()
        if epoch is None:
            epoch = self._mgr.latest_step()
            if epoch is None:
                raise FileNotFoundError(f"no checkpoints in {self.dir}")
        elif int(epoch) not in self._mgr.all_steps():
            raise FileNotFoundError(f"no checkpoint for epoch {epoch} in {self.dir}")
        out = self._mgr.restore(
            int(epoch),
            args=ocp.args.Composite(
                # Restore to host numpy, not to the saved sharding: a
                # checkpoint written by an 8-device run must load in a
                # 1-device recovery process (and vice versa) — the same
                # topology-independence the npz store has.
                state=ocp.args.PyTreeRestore(
                    restore_args={"board": ocp.RestoreArgs(restore_type=np.ndarray)}
                ),
                meta=ocp.args.JsonRestore(),
            ),
        )
        meta = dict(out["meta"])
        rule = meta.pop("rule")
        raw = np.asarray(out["state"]["board"])
        if meta.get("layout") == "packed32":
            # Saved by a packed-kernel run: (H, W/32) uint32 LSB-first words
            # (binary) or (m, H, W/32) Generations bit planes, written
            # device-native without host unpack.
            words = raw.astype(np.uint32, copy=False)
            if keep_packed:
                return Checkpoint(
                    epoch=int(epoch), board=None, rule=rule, meta=meta,
                    packed32=words,
                )
            if words.ndim == 3:
                from akka_game_of_life_tpu.ops.bitpack_gen import unpack_gen_np

                board = unpack_gen_np(words)
            else:
                from akka_game_of_life_tpu.ops.bitpack import unpack_np

                board = unpack_np(words)
            return Checkpoint(epoch=int(epoch), board=board, rule=rule, meta=meta)
        return Checkpoint(
            epoch=int(epoch),
            board=raw.astype(np.uint8, copy=False),
            rule=rule,
            meta=meta,
        )

    def close(self) -> None:
        self.wait()
        self._mgr.close()
