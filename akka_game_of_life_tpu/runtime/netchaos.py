"""Network chaos plane + the hardened-communications primitives it exercises.

:mod:`runtime/chaos.py` reproduces the reference's in-app killer — faults in
what the runtime *hosts* (``BoardCreator.scala:97-102``).  This module
injects faults in what the runtime *says*: a seeded, config-driven
:class:`NetworkChaos` policy (per-message drop / delay / duplicate / reorder
probabilities, plus scheduled bidirectional *partitions* between node groups
with heal times — the Jepsen-style drill) and a :class:`ChaosChannel`
wrapper that interposes on :class:`runtime.wire.Channel` send/recv without
touching the frame format.  It installs on the frontend control plane, the
worker control channel, and the backend peer data plane
(``--chaos-net-*`` / ``[net_chaos]`` config; see
:class:`runtime.config.NetworkChaosConfig`).

The partition schedule follows the :class:`runtime.chaos.CrashInjector`
schedule/budget contract exactly: first due after ``partition_after_s``,
then every ``partition_every_s``, each healing after ``partition_heal_s``,
at most ``max_partitions`` times — deterministic given the clock readings
and the seed.

:class:`CircuitBreaker` is the data-plane hardening the chaos plane
exercises: per-peer closed → open on consecutive send failures → half-open
probe after a cooldown → closed on success, so a dead or partitioned peer
stops burning the hot path on connect timeouts (production collectives'
standard discipline; cf. PAPERS.md *Casper* on comm-path stalls dominating
stencil pipelines).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Dict, FrozenSet, Iterable, Optional, Tuple

from akka_game_of_life_tpu.runtime.config import NetworkChaosConfig


class Decision:
    """What the policy ruled for one outgoing message."""

    __slots__ = ("blocked", "drop", "delay_s", "duplicate", "reorder")

    def __init__(
        self,
        blocked: bool = False,
        drop: bool = False,
        delay_s: float = 0.0,
        duplicate: bool = False,
        reorder: bool = False,
    ) -> None:
        self.blocked = blocked
        self.drop = drop
        self.delay_s = delay_s
        self.duplicate = duplicate
        self.reorder = reorder


class NetworkChaos:
    """Seeded wire-fault policy, shared by every :class:`ChaosChannel` of a
    run (one instance per process; the in-process harness shares one across
    the whole cluster, so partition sides are consistent end to end).

    Thread-safe: channels consult it from reader threads, compute threads,
    and delay timers concurrently.  The partition state machine is polled on
    traffic (every ``on_send``/``blocked`` call) — no dedicated thread — so
    a fully idle wire also has no partitions to observe.
    """

    def __init__(
        self,
        config: NetworkChaosConfig,
        *,
        start_time: Optional[float] = None,
        registry=None,
        tracer=None,
    ) -> None:
        self.config = config
        self.rng = random.Random(config.seed)
        self.partitions = 0
        self._lock = threading.RLock()
        self._start = start_time if start_time is not None else time.monotonic()
        self._next_due: Optional[float] = (
            self._start + config.partition_after_s
            if config.enabled and config.max_partitions > 0
            else None
        )
        self._groups: Optional[Tuple[FrozenSet[str], FrozenSet[str]]] = None
        self._heal_at = 0.0
        self._nodes: set = set()
        self._partition_span = None
        if registry is None:
            from akka_game_of_life_tpu.obs import get_registry

            registry = get_registry()
        if tracer is None:
            from akka_game_of_life_tpu.obs.tracing import get_tracer

            tracer = get_tracer()
        self.tracer = tracer
        self._m_dropped = registry.counter("gol_net_chaos_dropped_total")
        self._m_delayed = registry.counter("gol_net_chaos_delayed_total")
        self._m_duplicated = registry.counter("gol_net_chaos_duplicated_total")
        self._m_reordered = registry.counter("gol_net_chaos_reordered_total")
        self._m_partitions = registry.counter("gol_net_partitions_total")
        self._m_heals = registry.counter("gol_net_partition_heals_total")

    # -- node registry (partition sides are drawn from it) -------------------

    def register_node(self, name: Optional[str]) -> None:
        """Tell the policy a node name exists on the wire.  Channels register
        their endpoints as they are wrapped; the scheduled partition picker
        splits whatever set is known when a partition fires."""
        if name:
            with self._lock:
                self._nodes.add(name)

    # -- partition state machine ---------------------------------------------

    @property
    def exhausted(self) -> bool:
        return self.partitions >= self.config.max_partitions

    def partitioned(self) -> bool:
        with self._lock:
            return self._groups is not None

    def poll(self, now: Optional[float] = None) -> None:
        """Advance the partition schedule: heal an expired partition, fire a
        due one.  Deterministic given clock readings (the CrashInjector
        contract); safe to call from any thread, any number of times."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._groups is not None and now >= self._heal_at:
                self._heal_locked()
            if (
                self._groups is None
                and self._next_due is not None
                and not self.exhausted
                and now >= self._next_due
            ):
                # A partition needs two sides; with fewer than two known
                # nodes the slot stays armed (not consumed) until the wire
                # has peers to split.
                nodes = sorted(self._nodes)
                if len(nodes) < 2:
                    return
                side_a = frozenset(self.rng.sample(nodes, len(nodes) // 2))
                side_b = frozenset(n for n in nodes if n not in side_a)
                self._start_locked(side_a, side_b, self.config.partition_heal_s, now)
                self._next_due = now + self.config.partition_every_s

    def start_partition(
        self,
        side_a: Iterable[str],
        side_b: Iterable[str],
        heal_s: Optional[float] = None,
        now: Optional[float] = None,
    ) -> None:
        """Manually open a bidirectional partition between two node groups
        (the drill/test entry; the schedule calls the same machinery).
        Counts against the budget and metrics exactly like a scheduled one."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            if self._groups is not None:
                self._heal_locked()
            self._start_locked(
                frozenset(side_a),
                frozenset(side_b),
                heal_s if heal_s is not None else self.config.partition_heal_s,
                now,
            )

    def _start_locked(
        self,
        side_a: FrozenSet[str],
        side_b: FrozenSet[str],
        heal_s: float,
        now: float,
    ) -> None:
        self._groups = (side_a, side_b)
        self._heal_at = now + heal_s
        self.partitions += 1
        self._m_partitions.inc()
        self._partition_span = self.tracer.start(
            "net.partition",
            side_a=",".join(sorted(side_a)),
            side_b=",".join(sorted(side_b)),
            heal_s=heal_s,
            n=self.partitions,
        )
        # At-the-source flight record, like CrashInjector._fired: the
        # partition opening is on record even if a victim dies mid-drill.
        self.tracer.flight.record(
            "net_partition",
            n=self.partitions,
            side_a=sorted(side_a),
            side_b=sorted(side_b),
            heal_s=heal_s,
        )

    def heal(self) -> None:
        """Heal the active partition immediately (no-op when none is open)."""
        with self._lock:
            if self._groups is not None:
                self._heal_locked()

    def _heal_locked(self) -> None:
        self._groups = None
        self._m_heals.inc()
        self.tracer.flight.record("net_partition_healed", n=self.partitions)
        if self._partition_span is not None:
            self._partition_span.finish()
            self._partition_span = None

    def blocked(self, a: str, b: str, now: Optional[float] = None) -> bool:
        """Is traffic between nodes ``a`` and ``b`` cut by the active
        partition?  Unknown/unnamed endpoints are never blocked."""
        if not a or not b:
            return False
        self.poll(now)
        with self._lock:
            if self._groups is None:
                return False
            ga, gb = self._groups
            return (a in ga and b in gb) or (a in gb and b in ga)

    # -- per-message policy ---------------------------------------------------

    def on_send(self, src: str, dst: str, now: Optional[float] = None) -> Decision:
        """Rule on one outgoing message.  One rng draw per fault class,
        under the lock (decisions are a seeded deterministic stream given
        the call order)."""
        if self.blocked(src, dst, now):
            self._m_dropped.inc()
            return Decision(blocked=True)
        cfg = self.config
        if not cfg.enabled:
            return Decision()
        with self._lock:
            if cfg.drop_p and self.rng.random() < cfg.drop_p:
                self._m_dropped.inc()
                return Decision(drop=True)
            delay = (
                self.rng.uniform(0.0, cfg.delay_s)
                if cfg.delay_p and self.rng.random() < cfg.delay_p
                else 0.0
            )
            duplicate = bool(
                cfg.duplicate_p and self.rng.random() < cfg.duplicate_p
            )
            reorder = bool(cfg.reorder_p and self.rng.random() < cfg.reorder_p)
        if delay:
            self._m_delayed.inc()
        if duplicate:
            self._m_duplicated.inc()
        if reorder:
            self._m_reordered.inc()
        return Decision(delay_s=delay, duplicate=duplicate, reorder=reorder)


class ChaosChannel:
    """A :class:`runtime.wire.Channel` with the chaos policy interposed on
    send/recv.  The frame format is untouched — the wrapper only decides
    whether/when frames flow:

    - *drop*: the send silently vanishes (packet loss semantics);
    - *delay*: the send fires from a timer thread after the ruled latency
      (``Channel.send`` is already thread-safe, so a delayed frame can never
      interleave mid-frame with a live one);
    - *duplicate*: the frame is sent twice back-to-back (consumers must be
      idempotent — ring pushes and control messages are);
    - *reorder*: the frame is held and the NEXT send overtakes it;
    - *partition*: sends between separated groups are refused —
      ``fail_blocked=True`` (the peer data plane) raises ``ConnectionError``
      so the sender's failure handling (peer drop, circuit breaker) engages
      exactly as for a broken link; ``fail_blocked=False`` (the control
      plane) drops silently, which the heartbeat/eviction machinery sees as
      a lossy wire.  ``recv`` additionally filters frames arriving across an
      active partition, so a one-sided install still cuts both directions.

    ``src``/``dst`` are mutable attributes: accepted channels learn the far
    end's name mid-conversation (REGISTER / PEER_HELLO) and label the
    wrapper then.
    """

    def __init__(
        self,
        inner,
        chaos: NetworkChaos,
        *,
        src: str = "",
        dst: str = "",
        fail_blocked: bool = False,
    ) -> None:
        self.inner = inner
        self.chaos = chaos
        self.src = src
        self.dst = dst
        self.fail_blocked = fail_blocked
        self._held: Optional[dict] = None
        self._hold_lock = threading.Lock()
        chaos.register_node(src)
        chaos.register_node(dst)

    def send(self, msg: dict) -> None:
        self.chaos.register_node(self.dst)
        d = self.chaos.on_send(self.src, self.dst)
        if d.blocked:
            if self.fail_blocked:
                raise ConnectionResetError(
                    f"chaos: partition blocks {self.src or '?'} -> "
                    f"{self.dst or '?'}"
                )
            return
        if d.drop:
            return
        with self._hold_lock:
            held, self._held = self._held, None
            if held is None and d.reorder:
                self._held = msg
                return
        if d.delay_s:
            t = threading.Timer(
                d.delay_s, self._late_send, args=(msg, d.duplicate)
            )
            t.daemon = True
            t.start()
        else:
            self.inner.send(msg)
            if d.duplicate:
                self.inner.send(msg)
        if held is not None:
            # The overtaken frame goes out right after the overtaking one.
            self.inner.send(held)

    def _late_send(self, msg: dict, duplicate: bool = False) -> None:
        try:
            self.inner.send(msg)
            if duplicate:
                self.inner.send(msg)
        except (OSError, ValueError):
            pass  # the channel died while the frame was in the air

    def recv(self) -> Optional[dict]:
        while True:
            msg = self.inner.recv()
            if msg is None:
                return None
            if self.chaos.blocked(self.src, self.dst):
                # In-flight frame crossing an active partition: lost.
                self.chaos._m_dropped.inc()
                continue
            return msg

    def close(self) -> None:
        with self._hold_lock:
            held, self._held = self._held, None
        if held is not None:
            # The flush is still a send: it must not cross an active
            # partition (one-sided installs have no recv filter to save it).
            if self.chaos.blocked(self.src, self.dst):
                self.chaos._m_dropped.inc()
            else:
                try:
                    self.inner.send(held)
                except (OSError, ValueError):
                    pass
        self.inner.close()

    def __getattr__(self, name):
        # Everything else (sock, set_send_deadline, ...) is the wrapped
        # channel's business.
        return getattr(self.inner, name)


def wrap_channel(
    channel,
    chaos: Optional[NetworkChaos],
    *,
    src: str = "",
    dst: str = "",
    fail_blocked: bool = False,
):
    """``channel`` wrapped in chaos when a policy is installed, else as-is —
    the no-chaos path stays a plain :class:`Channel` with zero overhead."""
    if chaos is None:
        return channel
    return ChaosChannel(
        channel, chaos, src=src, dst=dst, fail_blocked=fail_blocked
    )


# -- circuit breaker ----------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half_open"}


class _PeerBreaker:
    __slots__ = ("state", "consecutive", "retry_at", "span")

    def __init__(self) -> None:
        self.state = CLOSED
        self.consecutive = 0
        self.retry_at = 0.0
        self.span = None


class CircuitBreaker:
    """Per-peer circuit breaker for the worker data plane.

    State machine (per peer)::

        CLOSED --[failures consecutive send failures]--> OPEN
        OPEN   --[cooldown_s elapsed]-----------------> HALF_OPEN (one probe)
        HALF_OPEN --[probe succeeds]------------------> CLOSED
        HALF_OPEN --[probe fails]---------------------> OPEN (cooldown re-arms)

    While OPEN, :meth:`allow` refuses sends (counted in
    ``gol_breaker_skipped_sends_total``) so a dead peer costs one state read
    instead of a connect timeout on every ring publish.  The open interval
    is one ``breaker.open`` span (started on the opening failure, finished
    by the closing success) and each opening bumps
    ``gol_breaker_open_total``; ``gol_breaker_state{peer=...}`` mirrors the
    live state (0=closed, 1=open, 2=half-open).
    """

    def __init__(
        self,
        *,
        failures: int = 3,
        cooldown_s: float = 2.0,
        registry=None,
        tracer=None,
        node: str = "",
        clock=time.monotonic,
    ) -> None:
        self.failures = max(1, int(failures))
        self.cooldown_s = cooldown_s
        self.node = node
        self._clock = clock
        self._lock = threading.Lock()
        self._peers: Dict[str, _PeerBreaker] = {}
        if registry is None:
            from akka_game_of_life_tpu.obs import get_registry

            registry = get_registry()
        if tracer is None:
            from akka_game_of_life_tpu.obs.tracing import get_tracer

            tracer = get_tracer()
        self.tracer = tracer
        self._m_state = registry.gauge(
            "gol_breaker_state",
            "Per-peer circuit breaker state (0=closed, 1=open, 2=half-open)",
            ("peer",),
        )
        self._m_opens = registry.counter("gol_breaker_open_total")
        self._m_skipped = registry.counter("gol_breaker_skipped_sends_total")

    def _peer(self, peer: str) -> _PeerBreaker:
        b = self._peers.get(peer)
        if b is None:
            b = self._peers[peer] = _PeerBreaker()
        return b

    def state(self, peer: str) -> int:
        with self._lock:
            b = self._peers.get(peer)
            return b.state if b is not None else CLOSED

    def peers(self) -> list:
        """Peers with breaker state (the cleanup surface for OWNERS
        rewiring: reset entries whose peer left the cluster)."""
        with self._lock:
            return list(self._peers)

    def allow(self, peer: str) -> bool:
        """May we attempt a send to ``peer`` right now?  OPEN past its
        cooldown transitions to HALF_OPEN and admits exactly one probe;
        callers MUST report the probe's outcome via :meth:`success` /
        :meth:`failure` or the breaker stays half-open until the next
        cooldown re-arms it."""
        now = self._clock()
        with self._lock:
            b = self._peers.get(peer)
            if b is None or b.state == CLOSED:
                return True
            if b.state == OPEN and now >= b.retry_at:
                b.state = HALF_OPEN
                # Re-arm: if the probe's outcome is never reported (caller
                # died mid-send), the next cooldown admits another probe.
                b.retry_at = now + self.cooldown_s
                self._m_state.labels(peer=peer).set(HALF_OPEN)
                return True
            self._m_skipped.inc()
            return False

    def success(self, peer: str) -> None:
        with self._lock:
            b = self._peers.get(peer)
            if b is None:
                return
            was_open = b.state != CLOSED
            b.state = CLOSED
            b.consecutive = 0
            span, b.span = b.span, None
        if was_open:
            self._m_state.labels(peer=peer).set(CLOSED)
            if span is not None:
                span.set(outcome="closed").finish()

    def failure(self, peer: str) -> None:
        now = self._clock()
        opened = False
        with self._lock:
            b = self._peer(peer)
            if b.state == HALF_OPEN:
                # The probe failed: back to OPEN for another cooldown.
                b.state = OPEN
                b.retry_at = now + self.cooldown_s
            elif b.state == CLOSED:
                b.consecutive += 1
                if b.consecutive >= self.failures:
                    b.state = OPEN
                    b.retry_at = now + self.cooldown_s
                    opened = True
            else:  # OPEN: an in-flight send failed after the state flipped
                b.retry_at = now + self.cooldown_s
            state = b.state
        if state != CLOSED:
            self._m_state.labels(peer=peer).set(state)
        if opened:
            self._m_opens.inc()
            span = self.tracer.start(
                "breaker.open", node=self.node or "backend", peer=peer,
                failures=self.failures,
            )
            self.tracer.flight.record(
                "breaker_open", peer=peer, node=self.node or "backend"
            )
            with self._lock:
                b = self._peer(peer)
                if b.span is None:
                    b.span = span
                else:
                    span.finish()

    def reset(self, peer: str) -> None:
        """Forget a peer entirely (it left the cluster)."""
        with self._lock:
            b = self._peers.pop(peer, None)
            span = b.span if b is not None else None
        if b is not None:
            self._m_state.labels(peer=peer).set(CLOSED)
        if span is not None:
            span.set(outcome="reset").finish()
