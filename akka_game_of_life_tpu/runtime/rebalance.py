"""Elastic rebalancing: the frontend's live tile-migration planner.

The reference cluster only *reacts* to failure (node-loss redeploy,
supervision replay); production elasticity needs the proactive motions —
scale-out (a late joiner receives load mid-run) and scale-in (a draining
worker hands its tiles back before leaving).  Both ride ONE mechanism, the
three-phase live migration the :class:`Rebalancer` plans and the frontend
executes:

  PREPARE   frontend → source: ``MIGRATE_PREPARE`` freezes the tile at its
            next chunk boundary (the worker refuses to start new chunks;
            in-flight compute completes normally under the worker lock).
  TRANSFER  source → frontend: ``MIGRATE_STATE`` ships the tile bit-packed
            (the PR 4 ``pack_tile`` codec, 8 cells/byte) at its live epoch,
            plus the source-computed 64-bit digest lanes.  The frontend
            re-derives the lanes from the payload (``digest_payload_np``)
            and refuses a mismatch LOUDLY — a corrupted transfer must roll
            back, never fork the trajectory.
  COMMIT    frontend: atomically rewire ownership (one OWNERS broadcast),
            then DEPLOY the certified payload to the destination at the
            frozen epoch.  The source drops the tile on the OWNERS receipt
            — until that moment it still owns the canonical state, so a
            destination death, a digest mismatch, or a deadline all roll
            back by simply unfreezing the source (``MIGRATE_ABORT``); no
            epoch is ever lost.

This module holds only the *policy* and the in-flight bookkeeping — pure
data structures the frontend mutates under its own lock.  All wire traffic,
membership, and metrics stay in :mod:`runtime.frontend`.

The planner knows THREE resource types: big-board *tiles* (:meth:`plan`),
the serving plane's *session shards* (:meth:`plan_shards` — groups of
tenant sessions hashed to a shard id, moved between workers by the same
freeze → transfer → certify → commit protocol at session granularity; see
:mod:`akka_game_of_life_tpu.serve.cluster`), and *resident tiled chunks*
(:meth:`plan_resident` — a worker-resident mega-board session's chunks,
re-homed digest-certified under the session's step barrier lock so a move
can never interleave with an epoch round).  The in-flight bookkeeping is
shared code: shard moves ride :class:`Migration` records keyed by the
integer shard id, chunk moves by the (sid, (cy, cx)) tuple, each in its
own serve-plane-owned Rebalancer instance.

Failure handling follows the PR 3 discipline: an aborted migration puts its
tile on a decorrelated-jitter cooldown (``delay = min(retry_max_s,
uniform(retry_s, 3·last))``, reset on success) so a flapping destination
sees a handful of desynchronized attempts per window, not a retry storm;
peer-plane traffic the migration induces (the destination's ring-history
pull from the source) rides the existing per-peer circuit breakers.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Tuple

from akka_game_of_life_tpu.runtime.tiles import TileId


@dataclasses.dataclass
class Migration:
    """One in-flight tile move, PREPARE through COMMIT/abort."""

    tile: TileId
    source: str
    dest: str
    seq: int
    started: float  # monotonic, for gol_migration_seconds
    deadline: float  # monotonic; overdue → abort + cooldown
    span: object = None  # the frontend's migrate.tile span


class Rebalancer:
    """Plans tile moves and tracks the in-flight set.

    NOT thread-safe on its own: the frontend mutates it strictly under the
    frontend lock (the same discipline as ``tile_owner``/``tile_epochs``).
    ``rebalance_enabled`` gates only the automatic imbalance planning;
    drain-driven moves (a member marked draining) are always planned —
    graceful scale-in must work on any cluster.
    """

    def __init__(self, config) -> None:
        self.enabled = config.rebalance_enabled
        self.interval_s = config.rebalance_interval_s
        self.min_gap = config.rebalance_min_gap
        self.max_inflight = config.rebalance_max_inflight
        self.deadline_s = config.rebalance_deadline_s
        # The PR 3 backoff policy, applied to migration retries.
        self._retry_s = config.retry_s
        self._retry_max_s = config.retry_max_s
        self._rng = random.Random(f"rebalance:{config.seed}")
        self.inflight: Dict[TileId, Migration] = {}
        self._seq = 0
        self._next_plan_at = 0.0
        self._next_shard_plan_at = 0.0
        self._next_resident_plan_at = 0.0
        self._cooldown: Dict[TileId, float] = {}  # tile → not-before
        self._delay: Dict[TileId, float] = {}  # tile → last chosen backoff

    # -- in-flight bookkeeping ------------------------------------------------

    def begin(
        self, tile: TileId, source: str, dest: str, now: float
    ) -> Migration:
        self._seq += 1
        mig = Migration(
            tile=tile,
            source=source,
            dest=dest,
            seq=self._seq,
            started=now,
            deadline=now + self.deadline_s,
        )
        self.inflight[tile] = mig
        return mig

    def get(self, tile, seq: int) -> Optional[Migration]:
        """The in-flight migration a MIGRATE_STATE / SHARD_STATE answers,
        or None for a stale/unknown (key, seq) — a state frame from an
        already-aborted attempt must be ignored, never committed.  Keys
        are TileId tuples for tile moves, plain ints for shard moves."""
        key = tuple(tile) if isinstance(tile, (list, tuple)) else tile
        mig = self.inflight.get(key)
        return mig if mig is not None and mig.seq == seq else None

    def complete(self, tile: TileId) -> Optional[Migration]:
        """Commit: drop the in-flight record and reset the tile's backoff."""
        self._cooldown.pop(tile, None)
        self._delay.pop(tile, None)
        return self.inflight.pop(tile, None)

    def abort(self, tile: TileId, now: float) -> Optional[Migration]:
        """Rollback: drop the record and put the tile on a decorrelated-
        jitter cooldown before the planner may try it again."""
        last = self._delay.get(tile, self._retry_s)
        delay = min(self._retry_max_s, self._rng.uniform(self._retry_s, 3 * last))
        self._delay[tile] = delay
        self._cooldown[tile] = now + delay
        return self.inflight.pop(tile, None)

    def expired(self, now: float) -> List[Migration]:
        return [m for m in self.inflight.values() if now >= m.deadline]

    def drop_member(self, name: str) -> List[Migration]:
        """Migrations that must abort because ``name`` left the cluster
        (either end of an in-flight move)."""
        return [
            m for m in self.inflight.values() if name in (m.source, m.dest)
        ]

    # -- planning -------------------------------------------------------------

    def plan(
        self,
        members,
        tile_epochs: Dict[TileId, int],
        final_epoch: int,
        now: float,
        drain_only: bool = False,
    ) -> List[Tuple[TileId, str, str]]:
        """(tile, source, dest) moves to start this pass.

        Drain-driven moves come first and are planned every pass; automatic
        imbalance moves run only when enabled, at ``interval_s`` cadence,
        and never when ``drain_only`` (a paused cluster still honors
        drains — a paused tile is not stepping, so moving it is safe — but
        must not reshape for load).
        Loads are projected through the in-flight set so a slow migration
        is not double-planned, and every destination filters to placeable
        (alive, not draining) members.
        """
        moves: List[Tuple[TileId, str, str]] = []
        budget = self.max_inflight - len(self.inflight)
        if budget <= 0:
            return moves
        placeable = [m for m in members if m.alive and not m.draining]
        if not placeable:
            return moves
        loads = {m.name: len(m.tiles) for m in placeable}
        for mig in self.inflight.values():
            if mig.dest in loads:
                loads[mig.dest] += 1
            if mig.source in loads:
                loads[mig.source] = max(0, loads[mig.source] - 1)
        planned = set()

        def movable(m, require_unfinished: bool = False):
            out = [
                t
                for t in m.tiles
                if t not in self.inflight
                and t not in planned
                and self._cooldown.get(t, 0.0) <= now
            ]
            if require_unfinished and final_epoch:
                # Load balancing skips tiles already at the final epoch
                # (nothing left to speed up); drains still move them —
                # the member cannot leave while it owns anything.
                out = [t for t in out if tile_epochs.get(t, 0) < final_epoch]
            # Most caught-up first: freezing the tile closest to the
            # target blocks the fewest neighbor halo assemblies.
            out.sort(key=lambda t: tile_epochs.get(t, 0), reverse=True)
            return out

        # 1. Drain-driven: empty draining members as fast as the in-flight
        # budget allows.  A draining member is its own source only.
        for m in members:
            if not (m.alive and m.draining):
                continue
            for tile in movable(m):
                if budget <= 0 or not loads:
                    break
                dest = min(loads, key=lambda n: loads[n])
                moves.append((tile, m.name, dest))
                planned.add(tile)
                loads[dest] += 1
                budget -= 1

        # 2. Load-driven: most- → least-loaded while the gap holds.  The
        # effective gap floor is 2 whatever min_gap says: moving a tile
        # across a gap of 1 swaps which member is fuller without lowering
        # the peak load — a planner honoring gap 1 ping-pongs the same
        # tile forever once loads are as even as the tile count allows.
        if self.enabled and not drain_only and budget > 0 and now >= self._next_plan_at:
            self._next_plan_at = now + self.interval_s
            gap = max(2, self.min_gap)
            while budget > 0 and len(loads) >= 2:
                src = max(placeable, key=lambda m: loads[m.name])
                dest = min(loads, key=lambda n: loads[n])
                if dest == src.name or loads[src.name] - loads[dest] < gap:
                    break
                cands = movable(src, require_unfinished=True)
                if not cands:
                    break
                tile = cands[0]
                moves.append((tile, src.name, dest))
                planned.add(tile)
                loads[src.name] -= 1
                loads[dest] += 1
                budget -= 1
        return moves

    def plan_shards(
        self,
        owners: Dict[int, str],
        weights: Dict[int, int],
        members,
        now: float,
        drain_only: bool = False,
        replicas: Optional[Dict[int, Optional[str]]] = None,
    ) -> List[Tuple[int, str, str]]:
        """(shard, source, dest) **session-shard** moves — the planner's
        second resource type (the cluster-sharded serving plane; the serve
        plane owns its own Rebalancer instance, so the in-flight budget
        and cooldowns never contend with tile moves).

        Same policy shape as :meth:`plan` with one deliberate difference:
        load-driven spreading ignores ``rebalance_enabled``.  For tiles,
        rebalancing is an optimization of a run that works anyway; for
        serving, a worker with zero shards serves zero traffic — spreading
        shards onto a late joiner IS how ``--grow-to`` buys boards/sec, so
        it is product behavior, not tuning.  It stays cadenced by
        ``interval_s`` and floored at a gap of 2 (a gap-1 shard move
        ping-pongs exactly like a gap-1 tile move).  Drain-driven moves
        come first and empty the drainer lightest-shards-first
        (``weights`` = sessions per shard), so a draining worker is
        released in the fewest protocol rounds blocked behind big
        exports.

        ``replicas`` (shard → replica worker, from the serve plane's
        replication table) is a PLACEMENT CONSTRAINT: a shard and its
        replica should not co-reside, so a shard's replica is avoided as
        its migration destination whenever any other placeable member
        exists.  When the replica is the ONLY destination (a 2-worker
        drain), the move still happens — wedging a drain forever would be
        worse than a transient co-residence, and the serve plane's
        post-commit replica refresh re-homes the replica in the same lock
        hold that commits the move."""
        moves: List[Tuple[int, str, str]] = []
        # The in-flight budget bounds only LOADED shards (each move
        # freezes sessions and runs the transfer protocol).  An EMPTY
        # shard (weight 0) flips ownership with no wire traffic at all,
        # so empties move budget-free — this is what lets a late joiner
        # absorb half an idle cluster's shard table in one pass.
        budget = self.max_inflight - len(self.inflight)
        free_budget = len(owners)  # hard per-pass bound, not a resource
        placeable = [m for m in members if m.alive and not m.draining]
        if not placeable:
            return moves
        loads = {m.name: 0 for m in placeable}
        for owner in owners.values():
            if owner in loads:
                loads[owner] += 1
        for mig in self.inflight.values():
            if mig.dest in loads:
                loads[mig.dest] += 1
            if mig.source in loads:
                loads[mig.source] = max(0, loads[mig.source] - 1)
        planned: set = set()

        def movable(name: str) -> List[int]:
            out = [
                s
                for s, o in owners.items()
                if o == name
                and s not in self.inflight
                and s not in planned
                and self._cooldown.get(s, 0.0) <= now
            ]
            out.sort(key=lambda s: (weights.get(s, 0), s))
            return out

        def charge(shard: int) -> bool:
            nonlocal budget, free_budget
            if weights.get(shard, 0) == 0:
                if free_budget <= 0:
                    return False
                free_budget -= 1
                return True
            if budget <= 0:
                return False
            budget -= 1
            return True

        def pick_dest(shard: int, exclude=()) -> Optional[str]:
            """Least-loaded placeable destination, avoiding the shard's
            replica (the no-co-residence constraint) unless the replica
            is the only destination left."""
            pool = [n for n in loads if n not in exclude]
            if not pool:
                return None
            banned = (replicas or {}).get(shard)
            cands = [n for n in pool if n != banned] or pool
            return min(cands, key=lambda n: (loads[n], n))

        # 1. Drain-driven: always planned, every pass (lightest shards
        # first, so the free empties flip out immediately).
        for m in members:
            if not (m.alive and m.draining):
                continue
            for shard in movable(m.name):
                if not loads:
                    break
                dest = pick_dest(shard)
                if dest is None or not charge(shard):
                    continue
                moves.append((shard, m.name, dest))
                planned.add(shard)
                loads[dest] += 1

        # 2. Load-driven spreading (shard-count gap ≥ 2), cadenced.  The
        # (shard, dest) pair is chosen together: each candidate shard's
        # replica bans ITS least-loaded destination individually, so one
        # shard's constraint never blocks the whole pass.
        if not drain_only and now >= self._next_shard_plan_at:
            self._next_shard_plan_at = now + self.interval_s
            gap = max(2, self.min_gap)
            while len(loads) >= 2:
                src = max(placeable, key=lambda m: loads.get(m.name, 0))
                choice = None
                for s in movable(src.name):
                    d = pick_dest(s, exclude=(src.name,))
                    if d is None or loads[src.name] - loads[d] < gap:
                        continue
                    choice = (s, d)
                    break
                if choice is None or not charge(choice[0]):
                    break
                shard, dest = choice
                moves.append((shard, src.name, dest))
                planned.add(shard)
                loads[src.name] -= 1
                loads[dest] += 1
        return moves

    def plan_slices(
        self,
        owners: Dict[int, str],
        weights: Dict[int, int],
        frontends,
        me: str,
    ) -> List[Tuple[int, str, str]]:
        """(slice, source, dest) **frontend-slice** releases — the
        planner's fourth resource type (the federation's serve-keyspace
        slices; ``serve/federation.py``).

        Deliberately the narrowest policy of the four: only EMPTY
        self-owned slices move, and only to their rendezvous-desired
        owner.  A non-empty slice never migrates between frontends —
        sessions are process-resident, so moving a loaded slice would
        mean moving boards across frontends for a placement preference;
        ownership of loaded slices changes only through confirmed-death
        promotion.  Empty releases are budget-free (ownership flips in
        one gossip round, like ``plan_shards``'s weight-0 empties), and
        the rendezvous target is deterministic over the live set, so a
        release can never ping-pong.

        ``owners`` is this frontend's view restricted to slices it owns;
        ``frontends`` is the sorted live frontend-name list (self
        included); ``me`` is this frontend's name."""
        from akka_game_of_life_tpu.serve.sessions import rendezvous_pick

        moves: List[Tuple[int, str, str]] = []
        live = sorted(frontends)
        if len(live) < 2:
            return moves
        for shard, owner in sorted(owners.items()):
            if owner != me or weights.get(shard, 0):
                continue
            desired = rendezvous_pick(f"slice:{shard}", live)
            if desired is not None and desired != me:
                moves.append((shard, me, desired))
        return moves

    def plan_resident(
        self,
        owners: Dict[tuple, str],
        members,
        now: float,
        drain_only: bool = False,
        replicas: Optional[Dict[tuple, Optional[str]]] = None,
    ) -> List[Tuple[tuple, str, str]]:
        """(chunk key, source, dest) **resident tiled chunk** moves — the
        planner's third resource type.  Keys are (sid, (cy, cx)) tuples;
        every move is a real state transfer (export → certify → adopt
        under the session's step barrier), so every move charges the
        in-flight budget.  Same drain-always / load-cadenced policy shape
        as :meth:`plan_shards`, with the chunk's replica avoided as a
        destination (the no-co-residence constraint, falling back when it
        is the last placeable member — a 2-worker drain must not wedge)."""
        moves: List[Tuple[tuple, str, str]] = []
        budget = self.max_inflight - len(self.inflight)
        if budget <= 0:
            return moves
        placeable = [m for m in members if m.alive and not m.draining]
        if not placeable:
            return moves
        loads = {m.name: 0 for m in placeable}
        for owner in owners.values():
            if owner in loads:
                loads[owner] += 1
        for mig in self.inflight.values():
            if mig.dest in loads:
                loads[mig.dest] += 1
            if mig.source in loads:
                loads[mig.source] = max(0, loads[mig.source] - 1)
        planned: set = set()

        def movable(name: str) -> List[tuple]:
            return sorted(
                k
                for k, o in owners.items()
                if o == name
                and k not in self.inflight
                and k not in planned
                and self._cooldown.get(k, 0.0) <= now
            )

        def pick_dest(key: tuple, exclude=()) -> Optional[str]:
            pool = [n for n in loads if n not in exclude]
            if not pool:
                return None
            banned = (replicas or {}).get(key)
            cands = [n for n in pool if n != banned] or pool
            return min(cands, key=lambda n: (loads[n], n))

        # 1. Drain-driven: always planned, every pass.
        for m in members:
            if not (m.alive and m.draining):
                continue
            for key in movable(m.name):
                if budget <= 0 or not loads:
                    break
                dest = pick_dest(key)
                if dest is None:
                    continue
                moves.append((key, m.name, dest))
                planned.add(key)
                loads[dest] += 1
                budget -= 1

        # 2. Load-driven spreading (chunk-count gap ≥ 2), cadenced.
        if not drain_only and budget > 0 and now >= self._next_resident_plan_at:
            self._next_resident_plan_at = now + self.interval_s
            gap = max(2, self.min_gap)
            while budget > 0 and len(loads) >= 2:
                src = max(placeable, key=lambda m: loads.get(m.name, 0))
                choice = None
                for k in movable(src.name):
                    d = pick_dest(k, exclude=(src.name,))
                    if d is None or loads[src.name] - loads[d] < gap:
                        continue
                    choice = (k, d)
                    break
                if choice is None:
                    break
                key, dest = choice
                moves.append((key, src.name, dest))
                planned.add(key)
                loads[src.name] -= 1
                loads[dest] += 1
                budget -= 1
        return moves
