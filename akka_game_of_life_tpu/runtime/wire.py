"""Wire format for the control plane: binary-framed JSON + raw array blobs.

The reference rides Akka remoting's Netty TCP transport with Java
serialization (``application.conf:11-17``; SURVEY.md §2 "Distributed
communication backend").  This channel keeps the control metadata as JSON
(boringly debuggable) but ships numpy arrays as *raw bytes* beside it —
no base64 (+33% size), no JSON string escaping, no text scanning on the hot
path, which matters once tiles at 65536²-class sizes ride the wire
(boundary rings, packed checkpoint tiles, sampled frames).

Frame layout (little-endian):

    u8   magic 0x47 ('G')
    u32  json section length
    u16  blob count
    u64  × blob-count blob lengths
    ...  json bytes, then each blob's bytes in order

Arrays appear in the JSON as ``{"__blob__": i, "dtype": "|u1", "shape":
[...]}`` placeholders; dtypes are preserved (uint8 boards, uint32 packed
words, int64 counters) instead of being forced to uint8.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional

import numpy as np

MAX_FRAME = 256 * 1024 * 1024
_MAGIC = 0x47
_HDR = struct.Struct("<BIH")
_BLOB_LEN = struct.Struct("<Q")


def _encode(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        blobs.append(arr.tobytes())
        return {
            "__blob__": len(blobs) - 1,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
        }
    if isinstance(obj, dict):
        return {k: _encode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode(v, blobs) for v in obj]
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    return obj


def _decode(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, dict):
        if "__blob__" in obj:
            raw = blobs[obj["__blob__"]]
            return (
                np.frombuffer(raw, dtype=np.dtype(obj["dtype"]))
                .reshape(obj["shape"])
                .copy()
            )
        return {k: _decode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v, blobs) for v in obj]
    return obj


class Channel:
    """A framed, thread-compatible message channel over a socket.

    ``send`` may be called from multiple threads (a lock serializes frames);
    ``recv`` is meant for a single reader thread.  ``recv`` returns None on
    EOF — connection loss is a first-class event for the membership layer
    (the DeathWatch analog), not an exception.
    """

    def __init__(
        self, sock: socket.socket, send_deadline_s: float = 0.0
    ) -> None:
        import threading

        self.sock = sock
        try:
            # Every frame is one sendall of a complete message; Nagle can
            # only add latency here, never save bytes.  Decisive on the
            # serve op plane, whose request/response frames are small and
            # ping-pongy — without this, Nagle × delayed-ACK stalls every
            # round trip by tens of ms once traffic fans across workers.
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except (OSError, ValueError):
            pass  # non-TCP test doubles (socketpairs) don't support it
        self._rfile = sock.makefile("rb")
        self._wlock = threading.Lock()
        # Optional send deadline (seconds; 0 = block forever): a send into a
        # wedged peer's full socket buffer raises an OSError (every existing
        # handler treats that as a dead channel) after roughly this long
        # instead of blocking the sending thread — heartbeats, ring
        # publishes — forever.  Implemented with SO_SNDTIMEO, which bounds
        # ONLY send-side blocking — settimeout() would race with a reader
        # thread blocked in recv on the same (bidirectional) socket.  A
        # timed-out send may have written a PARTIAL frame, so the channel
        # must not be reused after one: callers' OSError paths already
        # drop/close it.
        self.send_deadline_s = 0.0
        if send_deadline_s:
            self.set_send_deadline(send_deadline_s)

    def set_send_deadline(self, seconds: float) -> None:
        """Install/replace the per-send deadline (0 disables).  A method —
        not a bare attribute write — so chaos wrappers can delegate it to
        the real channel."""
        tv = struct.pack("ll", int(seconds), int((seconds % 1.0) * 1e6))
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        except (OSError, ValueError):  # platform without timeval sockopts
            return
        self.send_deadline_s = seconds

    def send(self, msg: Dict[str, Any]) -> None:
        blobs: List[bytes] = []
        payload = json.dumps(_encode(msg, blobs)).encode()
        total = len(payload) + sum(len(b) for b in blobs)
        if total > MAX_FRAME:
            raise ValueError(f"frame of {total} bytes exceeds MAX_FRAME {MAX_FRAME}")
        parts = [_HDR.pack(_MAGIC, len(payload), len(blobs))]
        parts.extend(_BLOB_LEN.pack(len(b)) for b in blobs)
        parts.append(payload)
        parts.extend(blobs)
        data = b"".join(parts)
        with self._wlock:
            self.sock.sendall(data)

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = self._rfile.read(n)
        if buf is None or len(buf) < n:
            return None  # EOF (clean at frame start, or truncated mid-frame)
        return buf

    def recv(self) -> Optional[Dict[str, Any]]:
        hdr = self._read_exact(_HDR.size)
        if hdr is None:
            return None
        magic, json_len, nblobs = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise ValueError(f"bad frame magic {magic:#x}")
        lens_raw = self._read_exact(_BLOB_LEN.size * nblobs)
        if lens_raw is None:
            return None
        blob_lens = [
            _BLOB_LEN.unpack_from(lens_raw, i * _BLOB_LEN.size)[0]
            for i in range(nblobs)
        ]
        if json_len + sum(blob_lens) > MAX_FRAME:
            raise ValueError("incoming frame exceeds MAX_FRAME")
        payload = self._read_exact(json_len)
        if payload is None:
            return None
        blobs: List[bytes] = []
        for ln in blob_lens:
            b = self._read_exact(ln)
            if b is None:
                return None
            blobs.append(b)
        try:
            return _decode(json.loads(payload), blobs)
        except (KeyError, IndexError, TypeError) as e:
            # A structurally bad payload (blob reference out of range, wrong
            # nesting) is a malformed FRAME, same class as a bad magic:
            # surface it as the ValueError the serve loops already handle.
            raise ValueError(
                f"malformed frame payload: {type(e).__name__}: {e}"
            ) from e

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def dial(
    host: str,
    port: int,
    *,
    timeout_s: float = 5.0,
    send_deadline_s: float = 0.0,
) -> Channel:
    """Connect to a peer listener and wrap the socket as a Channel.  The
    timeout bounds only the CONNECT (a dead seed address must not wedge a
    gossip tick); the established channel reverts to blocking reads, with
    the usual optional send deadline.  Raises OSError on failure — every
    caller treats an undialable peer as simply not-yet-alive."""
    sock = socket.create_connection((host, port), timeout=timeout_s)
    sock.settimeout(None)
    return Channel(sock, send_deadline_s=send_deadline_s)


# -- trace-context envelope helpers -------------------------------------------
#
# Span context rides INSIDE the message JSON under obs.tracing.TRACE_KEY
# (an underscored key no protocol payload uses), so propagation needs no
# frame-format change and decoders that predate tracing simply ignore it.


def attach_trace(msg: Dict[str, Any], span) -> Dict[str, Any]:
    """Embed ``span``'s propagation context into a message envelope (no-op
    when ``span`` is None).  Mutates and returns ``msg`` — callers attach
    just before ``Channel.send``."""
    from akka_game_of_life_tpu.obs.tracing import TRACE_KEY

    if span is not None:
        msg[TRACE_KEY] = span.ctx if hasattr(span, "ctx") else dict(span)
    return msg


def extract_trace(msg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The sender's span context from a received envelope, or None.  The
    returned dict is what ``Tracer.span(parent=...)`` accepts."""
    from akka_game_of_life_tpu.obs.tracing import TRACE_KEY

    ctx = msg.get(TRACE_KEY)
    return ctx if isinstance(ctx, dict) else None


# -- tile payload helpers -----------------------------------------------------


# -- boundary-ring payload codec ----------------------------------------------
#
# The peer data plane's wire unit (PEER_RING / PEER_RING_BATCH).  A Ring has
# 8 components (top/bottom/left/right + 4 corners); shipping them as 8 raw
# uint8 blobs costs 8 blob-length headers and 8 JSON placeholders per ring.
# Here the whole ring concatenates into ONE blob, and binary-rule rings
# additionally bit-pack 32 cells per uint32 word (the ops/bitpack layout:
# LSB-first within the word) — ~8x fewer payload bytes on the wire.  The
# entry self-describes via "enc", so the receiver decodes without knowing
# the sender's pack setting; an unknown "enc" raises ValueError, which every
# peer serve loop treats as a dead channel (mixed-version peers fail loud,
# never silently mis-decode).

# Fixed component order of the concatenated ring blob.
_RING_PARTS = ("top", "bottom", "left", "right", "nw", "ne", "sw", "se")


def _ring_shapes(h: int, w: int, k: int) -> List[tuple]:
    """Component shapes of a width-k ring of an (h, w) tile, in
    ``_RING_PARTS`` order."""
    return [(k, w), (k, w), (h, k), (h, k), (k, k), (k, k), (k, k), (k, k)]


def encode_ring(ring, pack: bool) -> Dict[str, Any]:
    """A :class:`runtime.tiles.Ring` → one wire entry.

    ``pack=True`` (binary rules only — cells must be 0/1) packs the
    concatenated components 32 cells per uint32 word; ``pack=False`` ships
    the concatenation as raw uint8 (any state alphabet).  Either way the
    ring is ONE blob + a 4-int header instead of 8 blobs."""
    k = ring.width
    h = ring.left.shape[0]
    w = ring.top.shape[1]
    parts = [ring.top, ring.bottom, ring.left, ring.right] + [
        ring.corners[c] for c in ("nw", "ne", "sw", "se")
    ]
    flat = np.concatenate(
        [np.ascontiguousarray(p, dtype=np.uint8).ravel() for p in parts]
    )
    if pack:
        bits = np.packbits(flat, bitorder="little")
        pad = (-bits.size) % 4
        if pad:
            bits = np.concatenate([bits, np.zeros(pad, np.uint8)])
        return {"enc": "bits1", "h": h, "w": w, "k": k, "data": bits.view(np.uint32)}
    return {"enc": "raw", "h": h, "w": w, "k": k, "data": flat}


def decode_ring(entry: Dict[str, Any]):
    """Inverse of :func:`encode_ring`; bit-exact round-trip.  Raises
    ``ValueError`` on an unknown encoding or a size mismatch (a
    wrong-version or corrupt peer must fail loud, not yield garbage
    halos)."""
    from akka_game_of_life_tpu.runtime.tiles import Ring

    h, w, k = int(entry["h"]), int(entry["w"]), int(entry["k"])
    shapes = _ring_shapes(h, w, k)
    n = sum(a * b for a, b in shapes)
    enc = entry.get("enc")
    data = entry["data"]
    if enc == "bits1":
        raw = np.asarray(data, dtype=np.uint32)
        if raw.view(np.uint8).size * 8 < n:
            raise ValueError(
                f"packed ring blob holds {raw.view(np.uint8).size * 8} bits, "
                f"needs {n}"
            )
        flat = np.unpackbits(raw.view(np.uint8), count=n, bitorder="little")
    elif enc == "raw":
        flat = np.asarray(data, dtype=np.uint8).ravel()
        if flat.size != n:
            raise ValueError(f"raw ring blob holds {flat.size} cells, needs {n}")
    else:
        raise ValueError(f"unknown ring encoding {enc!r}")
    views = []
    off = 0
    for shape in shapes:
        size = shape[0] * shape[1]
        views.append(flat[off : off + size].reshape(shape).copy())
        off += size
    top, bottom, left, right, nw, ne, sw, se = views
    return Ring(
        top=top, bottom=bottom, left=left, right=right,
        corners={"nw": nw, "ne": ne, "sw": sw, "se": se},
    )


def ring_entry_nbytes(entry: Dict[str, Any]) -> int:
    """Wire payload bytes of one encoded ring entry (the blob only — the
    JSON envelope is the per-frame overhead batching amortizes)."""
    return int(np.asarray(entry["data"]).nbytes)


# Per-entry JSON overhead allowance when splitting batches against
# MAX_FRAME: placeholder + tile/epoch/header ints, generously rounded up.
_ENTRY_JSON_OVERHEAD = 256
# Keep one batch frame well under MAX_FRAME: rings are small, so a quarter
# of the cap leaves room for the envelope while still batching thousands.
RING_BATCH_MAX_BYTES = MAX_FRAME // 4


def split_ring_batches(
    entries: List[Dict[str, Any]], max_bytes: int = RING_BATCH_MAX_BYTES
) -> List[List[Dict[str, Any]]]:
    """Split a list of batch entries (``{"tile", "epoch", "ring"}`` dicts,
    or payload-free ``{"tile", "epoch", "same_as"}`` quiescence markers)
    into sub-lists whose payload bytes each stay under ``max_bytes`` — one
    PEER_RING_BATCH frame per sub-list.  Order is preserved; an oversize
    single entry still gets its own frame (the Channel's MAX_FRAME check is
    the hard backstop).  Empty input → no frames."""
    frames: List[List[Dict[str, Any]]] = []
    cur: List[Dict[str, Any]] = []
    cur_bytes = 0
    for entry in entries:
        nbytes = (
            ring_entry_nbytes(entry["ring"]) if "ring" in entry else 0
        ) + _ENTRY_JSON_OVERHEAD
        if cur and cur_bytes + nbytes > max_bytes:
            frames.append(cur)
            cur, cur_bytes = [], 0
        cur.append(entry)
        cur_bytes += nbytes
    if cur:
        frames.append(cur)
    return frames


def pack_tile(arr: np.ndarray) -> Dict[str, Any]:
    """Encode a tile for bulk shipping: binary boards bit-pack 8 cells/byte
    (the only honest way a 65536²-class tile fits a frame); multi-state
    boards ride raw uint8."""
    arr = np.asarray(arr, dtype=np.uint8)
    if bool((arr <= 1).all()):
        return {
            "enc": "bits",
            "shape": list(arr.shape),
            "data": np.packbits(arr),
        }
    return {"enc": "raw", "shape": list(arr.shape), "data": arr}


def unpack_tile(payload: Dict[str, Any]) -> np.ndarray:
    shape = tuple(int(v) for v in payload["shape"])
    data = payload["data"]
    if payload["enc"] == "bits":
        n = int(np.prod(shape))
        return np.unpackbits(data, count=n).reshape(shape)
    return np.asarray(data, dtype=np.uint8).reshape(shape)
