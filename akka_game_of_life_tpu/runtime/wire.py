"""Wire format for the control plane: length-prefixed JSON frames over TCP.

The reference rides Akka remoting's Netty TCP transport with Java
serialization (``application.conf:11-17``; SURVEY.md §2 "Distributed
communication backend").  The TPU build's control plane is deliberately
boring: newline-delimited JSON frames, numpy arrays as base64 of raw bytes +
shape.  All bulk data (the grids) stays on-device in HBM; only boundary rings
and sampled frames cross this channel, so the wire format is not a
performance surface.
"""

from __future__ import annotations

import base64
import json
import socket
from typing import Any, Dict, Optional

import numpy as np

MAX_FRAME = 256 * 1024 * 1024


def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.ascontiguousarray(arr, dtype=np.uint8)
    return {
        "__nd__": base64.b64encode(arr.tobytes()).decode("ascii"),
        "shape": list(arr.shape),
    }


def decode_array(obj: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(obj["__nd__"])
    return np.frombuffer(raw, dtype=np.uint8).reshape(obj["shape"]).copy()


def _encode(obj: Any) -> Any:
    if isinstance(obj, np.ndarray):
        return encode_array(obj)
    if isinstance(obj, dict):
        return {k: _encode(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode(v) for v in obj]
    if isinstance(obj, (np.integer,)):
        return int(obj)
    return obj


def _decode(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__nd__" in obj:
            return decode_array(obj)
        return {k: _decode(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v) for v in obj]
    return obj


class Channel:
    """A framed, thread-compatible message channel over a socket.

    ``send`` may be called from multiple threads (a lock serializes frames);
    ``recv`` is meant for a single reader thread.  ``recv`` returns None on
    clean EOF — connection loss is a first-class event for the membership
    layer (the DeathWatch analog), not an exception.
    """

    def __init__(self, sock: socket.socket) -> None:
        import threading

        self.sock = sock
        self._rfile = sock.makefile("rb")
        self._wlock = threading.Lock()

    def send(self, msg: Dict[str, Any]) -> None:
        data = (json.dumps(_encode(msg)) + "\n").encode()
        with self._wlock:
            self.sock.sendall(data)

    def recv(self) -> Optional[Dict[str, Any]]:
        line = self._rfile.readline(MAX_FRAME)
        if not line:
            return None
        return _decode(json.loads(line))

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()
