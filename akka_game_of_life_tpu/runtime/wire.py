"""Wire format for the control plane: binary-framed JSON + raw array blobs.

The reference rides Akka remoting's Netty TCP transport with Java
serialization (``application.conf:11-17``; SURVEY.md §2 "Distributed
communication backend").  This channel keeps the control metadata as JSON
(boringly debuggable) but ships numpy arrays as *raw bytes* beside it —
no base64 (+33% size), no JSON string escaping, no text scanning on the hot
path, which matters once tiles at 65536²-class sizes ride the wire
(boundary rings, packed checkpoint tiles, sampled frames).

Frame layout (little-endian):

    u8   magic 0x47 ('G')
    u32  json section length
    u16  blob count
    u64  × blob-count blob lengths
    ...  json bytes, then each blob's bytes in order

Arrays appear in the JSON as ``{"__blob__": i, "dtype": "|u1", "shape":
[...]}`` placeholders; dtypes are preserved (uint8 boards, uint32 packed
words, int64 counters) instead of being forced to uint8.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, Dict, List, Optional

import numpy as np

MAX_FRAME = 256 * 1024 * 1024
_MAGIC = 0x47
_HDR = struct.Struct("<BIH")
_BLOB_LEN = struct.Struct("<Q")


def _encode(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        blobs.append(arr.tobytes())
        return {
            "__blob__": len(blobs) - 1,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
        }
    if isinstance(obj, dict):
        return {k: _encode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_encode(v, blobs) for v in obj]
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    return obj


def _decode(obj: Any, blobs: List[bytes]) -> Any:
    if isinstance(obj, dict):
        if "__blob__" in obj:
            raw = blobs[obj["__blob__"]]
            return (
                np.frombuffer(raw, dtype=np.dtype(obj["dtype"]))
                .reshape(obj["shape"])
                .copy()
            )
        return {k: _decode(v, blobs) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_decode(v, blobs) for v in obj]
    return obj


class Channel:
    """A framed, thread-compatible message channel over a socket.

    ``send`` may be called from multiple threads (a lock serializes frames);
    ``recv`` is meant for a single reader thread.  ``recv`` returns None on
    EOF — connection loss is a first-class event for the membership layer
    (the DeathWatch analog), not an exception.
    """

    def __init__(
        self, sock: socket.socket, send_deadline_s: float = 0.0
    ) -> None:
        import threading

        self.sock = sock
        self._rfile = sock.makefile("rb")
        self._wlock = threading.Lock()
        # Optional send deadline (seconds; 0 = block forever): a send into a
        # wedged peer's full socket buffer raises an OSError (every existing
        # handler treats that as a dead channel) after roughly this long
        # instead of blocking the sending thread — heartbeats, ring
        # publishes — forever.  Implemented with SO_SNDTIMEO, which bounds
        # ONLY send-side blocking — settimeout() would race with a reader
        # thread blocked in recv on the same (bidirectional) socket.  A
        # timed-out send may have written a PARTIAL frame, so the channel
        # must not be reused after one: callers' OSError paths already
        # drop/close it.
        self.send_deadline_s = 0.0
        if send_deadline_s:
            self.set_send_deadline(send_deadline_s)

    def set_send_deadline(self, seconds: float) -> None:
        """Install/replace the per-send deadline (0 disables).  A method —
        not a bare attribute write — so chaos wrappers can delegate it to
        the real channel."""
        tv = struct.pack("ll", int(seconds), int((seconds % 1.0) * 1e6))
        try:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDTIMEO, tv)
        except (OSError, ValueError):  # platform without timeval sockopts
            return
        self.send_deadline_s = seconds

    def send(self, msg: Dict[str, Any]) -> None:
        blobs: List[bytes] = []
        payload = json.dumps(_encode(msg, blobs)).encode()
        total = len(payload) + sum(len(b) for b in blobs)
        if total > MAX_FRAME:
            raise ValueError(f"frame of {total} bytes exceeds MAX_FRAME {MAX_FRAME}")
        parts = [_HDR.pack(_MAGIC, len(payload), len(blobs))]
        parts.extend(_BLOB_LEN.pack(len(b)) for b in blobs)
        parts.append(payload)
        parts.extend(blobs)
        data = b"".join(parts)
        with self._wlock:
            self.sock.sendall(data)

    def _read_exact(self, n: int) -> Optional[bytes]:
        buf = self._rfile.read(n)
        if buf is None or len(buf) < n:
            return None  # EOF (clean at frame start, or truncated mid-frame)
        return buf

    def recv(self) -> Optional[Dict[str, Any]]:
        hdr = self._read_exact(_HDR.size)
        if hdr is None:
            return None
        magic, json_len, nblobs = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise ValueError(f"bad frame magic {magic:#x}")
        lens_raw = self._read_exact(_BLOB_LEN.size * nblobs)
        if lens_raw is None:
            return None
        blob_lens = [
            _BLOB_LEN.unpack_from(lens_raw, i * _BLOB_LEN.size)[0]
            for i in range(nblobs)
        ]
        if json_len + sum(blob_lens) > MAX_FRAME:
            raise ValueError("incoming frame exceeds MAX_FRAME")
        payload = self._read_exact(json_len)
        if payload is None:
            return None
        blobs: List[bytes] = []
        for ln in blob_lens:
            b = self._read_exact(ln)
            if b is None:
                return None
            blobs.append(b)
        try:
            return _decode(json.loads(payload), blobs)
        except (KeyError, IndexError, TypeError) as e:
            # A structurally bad payload (blob reference out of range, wrong
            # nesting) is a malformed FRAME, same class as a bad magic:
            # surface it as the ValueError the serve loops already handle.
            raise ValueError(
                f"malformed frame payload: {type(e).__name__}: {e}"
            ) from e

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


# -- trace-context envelope helpers -------------------------------------------
#
# Span context rides INSIDE the message JSON under obs.tracing.TRACE_KEY
# (an underscored key no protocol payload uses), so propagation needs no
# frame-format change and decoders that predate tracing simply ignore it.


def attach_trace(msg: Dict[str, Any], span) -> Dict[str, Any]:
    """Embed ``span``'s propagation context into a message envelope (no-op
    when ``span`` is None).  Mutates and returns ``msg`` — callers attach
    just before ``Channel.send``."""
    from akka_game_of_life_tpu.obs.tracing import TRACE_KEY

    if span is not None:
        msg[TRACE_KEY] = span.ctx if hasattr(span, "ctx") else dict(span)
    return msg


def extract_trace(msg: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The sender's span context from a received envelope, or None.  The
    returned dict is what ``Tracer.span(parent=...)`` accepts."""
    from akka_game_of_life_tpu.obs.tracing import TRACE_KEY

    ctx = msg.get(TRACE_KEY)
    return ctx if isinstance(ctx, dict) else None


# -- tile payload helpers -----------------------------------------------------


def pack_tile(arr: np.ndarray) -> Dict[str, Any]:
    """Encode a tile for bulk shipping: binary boards bit-pack 8 cells/byte
    (the only honest way a 65536²-class tile fits a frame); multi-state
    boards ride raw uint8."""
    arr = np.asarray(arr, dtype=np.uint8)
    if bool((arr <= 1).all()):
        return {
            "enc": "bits",
            "shape": list(arr.shape),
            "data": np.packbits(arr),
        }
    return {"enc": "raw", "shape": list(arr.shape), "data": arr}


def unpack_tile(payload: Dict[str, Any]) -> np.ndarray:
    shape = tuple(int(v) for v in payload["shape"])
    data = payload["data"]
    if payload["enc"] == "bits":
        n = int(np.prod(shape))
        return np.unpackbits(data, count=n).reshape(shape)
    return np.asarray(data, dtype=np.uint8).reshape(shape)
