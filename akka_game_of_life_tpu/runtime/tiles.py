"""Tile layout: partitioning the global torus into coarse per-worker shards.

The reference's placement is one actor per *cell*, scattered uniformly at
random with zero locality (``BoardCreator.scala:33-36,65-70``) — ~18 network
messages per cell per epoch.  The TPU build's unit of placement is a
contiguous rectangular tile (a whole sub-grid per worker, held in HBM), so a
worker's per-epoch communication is its 1-cell boundary ring, and the Moore
neighborhood of a *tile* is the 8 surrounding tiles on the tile torus —
the same geometry as ``generateNeighbourAddresses`` (``package.scala:17-28``),
lifted from cells to tiles and made properly toroidal.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from akka_game_of_life_tpu.parallel.mesh import factor_2d

TileId = Tuple[int, int]  # (tile_row, tile_col)


@dataclasses.dataclass(frozen=True)
class TileLayout:
    """An R×C tiling of an (H, W) torus."""

    board_shape: Tuple[int, int]
    grid: Tuple[int, int]  # (R, C) tiles

    def __post_init__(self) -> None:
        h, w = self.board_shape
        r, c = self.grid
        if h % r or w % c:
            raise ValueError(f"board {self.board_shape} not divisible by tiles {self.grid}")

    @property
    def tile_shape(self) -> Tuple[int, int]:
        return (self.board_shape[0] // self.grid[0], self.board_shape[1] // self.grid[1])

    @property
    def tile_ids(self) -> List[TileId]:
        r, c = self.grid
        return [(i, j) for i in range(r) for j in range(c)]

    def origin(self, tile: TileId) -> Tuple[int, int]:
        th, tw = self.tile_shape
        return (tile[0] * th, tile[1] * tw)

    def extract(self, board, tile: TileId):
        y, x = self.origin(tile)
        th, tw = self.tile_shape
        return board[y : y + th, x : x + tw]

    def neighbors(self, tile: TileId) -> Dict[str, TileId]:
        """The 8 Moore neighbors on the tile torus, keyed by direction."""
        r, c = self.grid
        i, j = tile
        return {
            "nw": ((i - 1) % r, (j - 1) % c),
            "n": ((i - 1) % r, j),
            "ne": ((i - 1) % r, (j + 1) % c),
            "w": (i, (j - 1) % c),
            "e": (i, (j + 1) % c),
            "sw": ((i + 1) % r, (j - 1) % c),
            "s": ((i + 1) % r, j),
            "se": ((i + 1) % r, (j + 1) % c),
        }


def layout_for_workers(board_shape: Tuple[int, int], n_workers: int) -> TileLayout:
    """Choose a near-square tile grid with one tile per worker (falling back
    toward fewer tiles until the board divides evenly)."""
    for n in range(n_workers, 0, -1):
        r, c = factor_2d(n)
        if board_shape[0] % r == 0 and board_shape[1] % c == 0:
            return TileLayout(board_shape, (r, c))
    raise ValueError(f"no feasible tiling of {board_shape} for {n_workers} workers")


def stitch(tiles_by_origin) -> "np.ndarray":
    """Assemble origin-keyed tiles {(y, x): (h, w) array} into one board.

    The single tile-to-board stitcher shared by the render observer and the
    frontend's checkpoint/final assembly."""
    import numpy as np

    ys = sorted({o[0] for o in tiles_by_origin})
    xs = sorted({o[1] for o in tiles_by_origin})
    rows = []
    for y in ys:
        rows.append(
            np.concatenate([np.asarray(tiles_by_origin[(y, x)]) for x in xs], axis=1)
        )
    return np.concatenate(rows, axis=0)


@dataclasses.dataclass
class Ring:
    """A tile's width-k boundary ring at one epoch: what neighbors need.

    Width 1 is the reference's per-epoch exchange contract; width k>1 is the
    communication-avoiding trade (one exchange buys k local steps — the
    cluster analog of ``parallel/halo.py``'s on-device width-k halos and of
    the reference's history-buffered asynchrony, ``CellActor.scala:34-47``).
    The ring is purely spatial: a tile at epoch E always *has* its k
    outermost rows/cols, so publishing a wide ring needs no lookahead.
    """

    top: object  # (k, w) rows
    bottom: object  # (k, w)
    left: object  # (h, k) cols
    right: object  # (h, k)
    corners: Dict[str, object]  # nw/ne/sw/se (k, k) blocks

    @classmethod
    def of(cls, tile, width: int = 1) -> "Ring":
        k = width
        h, w = tile.shape
        if h < k or w < k:
            raise ValueError(f"tile {tile.shape} smaller than ring width {k}")
        return cls(
            top=tile[:k, :].copy(),
            bottom=tile[-k:, :].copy(),
            left=tile[:, :k].copy(),
            right=tile[:, -k:].copy(),
            corners={
                "nw": tile[:k, :k].copy(),
                "ne": tile[:k, -k:].copy(),
                "sw": tile[-k:, :k].copy(),
                "se": tile[-k:, -k:].copy(),
            },
        )

    @property
    def width(self) -> int:
        return len(self.top)  # (k, w): first axis is the ring width

    @property
    def nbytes(self) -> int:
        """Dense (unpacked) cell bytes of the ring — the logical payload
        size the wire-cost counters account, whatever the encoding."""
        import numpy as np

        return int(
            sum(
                np.asarray(p).size
                for p in (self.top, self.bottom, self.left, self.right)
            )
            + sum(np.asarray(c).size for c in self.corners.values())
        )
