"""Scheduled fault injection — the reference's in-app chaos harness.

The reference ships its chaos testing *inside* the app: a scheduled killer
picks a random cell and crashes it — first after ``error.delay``, then every
``error.every``, bounded by ``max-crashes`` (``BoardCreator.scala:97-102,108``,
``application.conf:41,44-47``).  :class:`CrashInjector` reproduces exactly
that schedule/budget contract.

What a "crash" means is the consumer's choice (the seam between standalone
and cluster modes): the standalone simulation loses its in-memory board and
must restore from checkpoint + deterministic replay; the control-plane
frontend kills a live backend worker process.

This injector faults what the runtime *hosts*; its wire-layer sibling —
:mod:`akka_game_of_life_tpu.runtime.netchaos` — faults what it *says*
(drops, delays, duplicates, reorders, partitions), on the same
schedule/budget contract.  Run both for the full drill.
"""

from __future__ import annotations

import random
import time
from typing import Optional

from akka_game_of_life_tpu.runtime.config import FaultInjectionConfig


class CrashInjector:
    """Wall-clock crash scheduler with a budget.

    ``should_crash(now)`` is True when a scheduled crash is due: the first
    ``first_after_s`` after start, then every ``every_s``, at most
    ``max_crashes`` times.  Deterministic given the clock readings; the
    ``rng`` is exposed for consumers that need to pick a random victim (the
    reference picks a random child cell — ``BoardCreator.scala:99``).
    """

    def __init__(
        self,
        config: FaultInjectionConfig,
        *,
        start_time: Optional[float] = None,
        registry=None,
        flight=None,
    ) -> None:
        self.config = config
        self.crashes = 0
        self.rng = random.Random(config.seed)
        self._start = start_time if start_time is not None else time.monotonic()
        self._next_due: Optional[float] = (
            self._start + config.first_after_s if config.enabled else None
        )
        # Fired crashes count at the source — both schedules, every consumer
        # (standalone replay, cluster node/tile kill) share one counter.
        if registry is None:
            from akka_game_of_life_tpu.obs import get_registry

            registry = get_registry()
        self._crash_counter = registry.counter("gol_chaos_crashes_total")
        # Same at-the-source rule for the flight ring: the schedule firing
        # is on record even if the consumer dies before its own dump.
        if flight is None:
            from akka_game_of_life_tpu.obs.tracing import get_tracer

            flight = get_tracer().flight
        self._flight = flight

    def _fired(self, **fields) -> None:
        self.crashes += 1
        self._crash_counter.inc()
        self._flight.record(
            "chaos_crash_due", n=self.crashes, mode=self.config.mode, **fields
        )

    @property
    def exhausted(self) -> bool:
        return self.crashes >= self.config.max_crashes

    def should_crash(self, now: Optional[float] = None) -> bool:
        if self.config.epoch_indexed:
            return False  # epoch-indexed schedules use should_crash_at_epoch
        if self._next_due is None or self.exhausted:
            return False
        now = now if now is not None else time.monotonic()
        if now < self._next_due:
            return False
        self._fired(schedule="wall_clock")
        self._next_due = now + self.config.every_s
        return True

    def should_crash_at_epoch(self, epoch: int) -> bool:
        """Epoch-indexed twin of :meth:`should_crash`: due once the
        simulation reaches ``first_after_epochs``, then every
        ``every_epochs`` further.  Pure in simulation time — every rank of a
        multi-host run computes the identical schedule, so injected crashes
        are lockstep SPMD events (the distributed-chaos requirement)."""
        if not self.config.epoch_indexed or not self.config.enabled:
            return False
        if self.exhausted:
            return False
        due = self.config.first_after_epochs + self.crashes * self.config.every_epochs
        if epoch < due:
            return False
        self._fired(schedule="epoch_indexed", epoch=epoch)
        return True
