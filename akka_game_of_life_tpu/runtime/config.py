"""Layered configuration — the HOCON/Typesafe-Config capability, TPU-native.

The reference layers: argv port → role string → ``application.conf`` defaults
(``Run.scala:30-32,59-61``).  Here the same precedence is dataclass defaults →
config file (TOML or JSON) → explicit overrides (CLI/env), with the
reference's full knob set (``application.conf:29-48``) plus the TPU-runtime
knobs the stencil backend needs.  Durations accept the reference's config
style ("5s", "3000ms", "1 second") as well as bare numbers (seconds).
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Dict, Mapping, Optional, Tuple

_DURATION_RE = re.compile(
    r"^\s*(?P<num>\d+(?:\.\d+)?)\s*(?P<unit>ms|milliseconds?|s|seconds?|m|minutes?|h|hours?)?\s*$",
    re.IGNORECASE,
)
_UNIT_SECONDS = {
    "ms": 1e-3,
    "millisecond": 1e-3,
    "milliseconds": 1e-3,
    "s": 1.0,
    "second": 1.0,
    "seconds": 1.0,
    "m": 60.0,
    "minute": 60.0,
    "minutes": 60.0,
    "h": 3600.0,
    "hour": 3600.0,
    "hours": 3600.0,
}


def parse_size_classes(spec) -> Tuple[int, ...]:
    """``"32,64,256"`` → (32, 64, 256): the serving plane's padded board
    size classes, strictly ascending positive square sides (see
    docs/OPERATIONS.md "Serving plane").  Lives here (not serve/) so
    config validation stays import-light — :mod:`serve.batch` re-exports
    it."""
    try:
        classes = tuple(int(v) for v in str(spec).split(","))
    except ValueError:
        raise ValueError(f"unparseable serve size classes: {spec!r}") from None
    if not classes or any(c <= 0 for c in classes) or any(
        b <= a for a, b in zip(classes, classes[1:])
    ):
        raise ValueError(
            f"serve size classes must be strictly ascending positive "
            f"ints, got {spec!r}"
        )
    return classes


# The stencil-kernel selection surface, one name per kernel family (see
# docs/OPERATIONS.md "Kernel selection" and docs/KERNELS.md).  The CLI
# mirrors this tuple as a literal (cli.py _KERNEL_CHOICES) so the lints
# stay import-free; graftlint GL-CFG06 enforces the bijection between the
# two literals and the operator doc's table.
KERNEL_CHOICES = (
    "auto",
    "dense",
    "bitpack",
    "pallas",
    "matmul",
)


def parse_duration(value) -> float:
    """Parse a duration into seconds: 5, 5.0, "5s", "3000ms", "1 second"."""
    if isinstance(value, (int, float)):
        return float(value)
    m = _DURATION_RE.match(str(value))
    if not m:
        raise ValueError(f"unparseable duration: {value!r}")
    unit = (m.group("unit") or "s").lower()
    return float(m.group("num")) * _UNIT_SECONDS[unit]


@dataclasses.dataclass
class FaultInjectionConfig:
    """The reference's scheduled crash injector knobs
    (``application.conf:44-47``, ``BoardCreator.scala:97-102,108``)."""

    enabled: bool = False
    first_after_s: float = 10.0  # error.delay
    every_s: float = 15.0  # error.every
    max_crashes: int = 100  # game-of-life.max-crashes (application.conf:41)
    seed: int = 0
    # Cluster-mode crash flavor: "tile" kills one shard in place (the
    # reference's supervised CellActor restart, §3.3); "node" kills a whole
    # worker process (the reference's backend-JVM loss, §3.4).
    mode: str = "tile"
    # Epoch-indexed schedule (alternative to the wall-clock one): first
    # crash once the simulation reaches ``first_after_epochs``, then every
    # ``every_epochs``.  Deterministic in simulation time, so every rank of
    # a multi-host (jax.distributed) run injects at the SAME epoch and the
    # crash/restore/replay cycle stays an SPMD-lockstep event — the only
    # chaos shape that composes with cross-host collectives (wall-clock
    # schedules desynchronize ranks and are rejected in distributed mode).
    first_after_epochs: Optional[int] = None
    every_epochs: Optional[int] = None

    def __post_init__(self) -> None:
        if self.mode not in ("tile", "node"):
            raise ValueError(f"unknown fault injection mode {self.mode!r}")
        if (self.first_after_epochs is None) != (self.every_epochs is None):
            raise ValueError(
                "epoch-indexed injection needs both first_after_epochs and "
                "every_epochs (or neither, for the wall-clock schedule)"
            )
        if self.every_epochs is not None and (
            self.first_after_epochs < 0 or self.every_epochs < 1
        ):
            raise ValueError(
                f"bad epoch schedule: first_after_epochs="
                f"{self.first_after_epochs}, every_epochs={self.every_epochs}"
            )

    @property
    def epoch_indexed(self) -> bool:
        return self.every_epochs is not None


@dataclasses.dataclass
class NetworkChaosConfig:
    """Wire-layer fault injection knobs — the network analog of
    :class:`FaultInjectionConfig`.

    Where the crash injector kills processes/tiles the runtime *hosts*, this
    policy corrupts the traffic *between* them: seeded probabilistic drops,
    delays, duplicates, and reorders per message, plus scheduled
    bidirectional partitions between node groups with heal times (the
    Jepsen-style drill).  Applied by wrapping :class:`runtime.wire.Channel`
    in a :class:`runtime.netchaos.ChaosChannel` — the frame format is never
    touched, only whether/when frames flow.

    Every field here maps to a ``--chaos-net-*`` CLI flag
    (``tools/check_chaos_config.py`` lint-enforces the bijection).
    """

    enabled: bool = False
    seed: int = 0
    # Per-message probabilistic faults (applied on send).
    drop_p: float = 0.0
    delay_p: float = 0.0
    delay_s: float = 0.02  # max injected latency (uniform 0..delay_s)
    duplicate_p: float = 0.0
    reorder_p: float = 0.0  # hold a message and let the next overtake it
    # Partition schedule — the CrashInjector's schedule/budget contract on
    # the wire: first partition after partition_after_s, then every
    # partition_every_s, each healing after partition_heal_s, at most
    # max_partitions times.  0 partitions when max_partitions == 0.
    partition_after_s: float = 10.0
    partition_every_s: float = 30.0
    partition_heal_s: float = 5.0
    max_partitions: int = 0
    # Which planes the chaos channel wraps: the worker↔worker data plane
    # ("peer"), the frontend↔worker control plane ("control"), or both
    # ("all").  Peer-plane partition blocks FAIL the send (a broken link
    # the circuit breaker sees); control-plane blocks drop silently (lost
    # frames the heartbeat/eviction machinery sees).
    scope: str = "peer"

    def __post_init__(self) -> None:
        for name in ("drop_p", "delay_p", "duplicate_p", "reorder_p"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"net chaos {name}={p} must be in [0, 1]")
        if self.delay_s < 0 or self.partition_heal_s < 0:
            raise ValueError("net chaos durations must be >= 0")
        if self.max_partitions < 0:
            raise ValueError(
                f"max_partitions={self.max_partitions} must be >= 0"
            )
        if self.scope not in ("peer", "control", "all"):
            raise ValueError(
                f"unknown net chaos scope {self.scope!r}; use peer, "
                f"control, or all"
            )

    @property
    def wraps_peer(self) -> bool:
        return self.enabled and self.scope in ("peer", "all")

    @property
    def wraps_control(self) -> bool:
        return self.enabled and self.scope in ("control", "all")


@dataclasses.dataclass
class SimulationConfig:
    """All simulation knobs, mirroring ``application.conf``'s game-of-life
    block and extending it with the TPU runtime's own."""

    # Board (application.conf:30-35; exclusive bounds — the reference's
    # inclusive-range off-by-one is a documented bug, SURVEY.md §2).
    height: int = 64
    width: int = 64
    rule: str = "conway"
    density: float = 0.5
    seed: int = 0
    pattern: Optional[str] = None  # optional named pattern instead of random
    pattern_offset: Tuple[int, int] = (2, 2)

    # Timing (application.conf:37-40). tick_s=0 means free-running: no
    # wall-clock pacing, the TPU-native default.  The reference's fixed 3 s
    # tick is reproducible by setting tick_s=3.
    wait_for_backends_s: float = 5.0
    start_delay_s: float = 1.0
    tick_s: float = 0.0
    max_epochs: Optional[int] = None

    # TPU execution.
    backend: str = "tpu"  # "tpu" (stencil) | "actor" / "actor-native" (per-cell parity)
    # Stencil kernel on the tpu backend:
    #   dense   — uint8 roll-sum (any rule, incl. multi-state and LtL)
    #   bitpack — 32 cells/uint32 SWAR (binary totalistic rules) or m bit
    #             planes (Generations/wireworld); width % 32 == 0
    #   pallas  — VMEM-blocked Mosaic kernels (fastest on real TPU
    #             hardware, interpret-mode elsewhere): binary totalistic
    #             shards over the mesh via parallel/pallas_halo.py;
    #             Generations/wireworld plane sweeps and box-LtL slabs
    #             are single-device opt-ins
    #   matmul  — banded matrix-multiply neighbor counts (A_R·S·A_Rᵀ,
    #             ops/matmul_stencil.py): the MXU/tensor-core family per
    #             CAT; any box-neighborhood rule incl. radius-R LtL;
    #             single-device, intermediates guard-priced up front
    #   auto    — pallas on a real TPU for binary totalistic rules,
    #             single-device or meshed (size-adaptive block rows,
    #             bitpack fallback if Mosaic fails), else bitpack/planes
    #             when the rule/shape allow it, else dense
    kernel: str = "auto"
    pallas_block_rows: int = 64  # VMEM row-block for kernel="pallas"
    # Mosaic scoped-VMEM budget override in MB (0 = compiler default, 16 MB).
    # block_rows >= 256 at 65536-class widths needs ~20+ MB of double-buffered
    # blocks, past the default limit.  Kernels take it via the
    # pallas_vmem_limit_bytes property (None = default).
    pallas_vmem_limit_mb: int = 0
    steps_per_call: int = 1
    halo_width: int = 1
    mesh_shape: Optional[Tuple[int, int]] = None  # None = auto-factor devices

    # Multi-host (pod-scale): bring up the JAX distributed runtime so the
    # mesh spans every host's chips (SURVEY.md §2 TPU-native equivalent of
    # the reference's multi-JVM Akka cluster).  On TPU pods leave the three
    # None fields unset (auto-detected); on CPU/GPU clusters set them or the
    # GOL_COORDINATOR / GOL_NUM_PROCESSES / GOL_PROCESS_ID env vars.
    distributed: bool = False
    coordinator_address: Optional[str] = None
    num_processes: Optional[int] = None
    process_id: Optional[int] = None

    # Control plane.
    role: str = "standalone"  # standalone | frontend | backend
    host: str = "127.0.0.1"
    port: int = 2551  # the reference's seed-node port (application.conf:20-21)
    heartbeat_s: float = 0.5
    # The reference evicts unreachable members after 1 s
    # (auto-down-unreachable-after, application.conf:23).
    failure_timeout_s: float = 1.0

    # Supervision (the reference caps restarts: OneForOneStrategy(Restart,
    # maxNrOfRetries=10, withinTimeRange=1 minute), BoardCreator.scala:42-45).
    # A tile redeployed more than restart_max times within restart_window_s
    # escalates: the run fails loudly instead of thrashing forever.
    restart_max: int = 10
    restart_window_s: float = 60.0
    # Communication-avoiding cluster exchange: boundary rings are this many
    # cells wide and one peer exchange licenses this many local epochs per
    # tile (the wire analog of the on-device width-k halos,
    # parallel/halo.py:82-110; 1 = the reference's per-epoch exchange).
    # Requires every observation cadence to land on chunk boundaries:
    # render/metrics/checkpoint cadences must be multiples of this.
    exchange_width: int = 1
    # Tile oversubscription: each worker hosts this many tiles (the tile
    # grid has n_workers * tiles_per_worker tiles, assigned round-robin).
    # >1 gives the coalescing data plane multiple rings per peer per epoch
    # to batch, and gives node-loss recovery finer redistribution units.
    tiles_per_worker: int = 1
    # -- halo data-plane wire encoding (frontend-owned cluster policy,
    # shipped to every worker in WELCOME like the retry/breaker policy) --
    # ring_pack: binary-rule boundary rings bit-pack 32 cells per uint32
    # word before hitting the wire (~8x fewer payload bytes); multi-state
    # rules always ride raw uint8 regardless.  The receiver decodes by the
    # entry's self-describing encoding, so this only controls senders.
    ring_pack: bool = True
    # ring_batch: coalesce every ring bound for one peer in an epoch/chunk
    # into a single PEER_RING_BATCH frame (PEER_PULL replies batch the same
    # way), collapsing frame+JSON overhead from O(tiles x epochs x peers)
    # to O(peers x chunks).  Off = one PEER_RING frame per ring (the
    # reference's per-message shape, kept for A/B measurement).
    ring_batch: bool = True
    # Bound on each per-peer outbound send queue (ring entries + control
    # asks).  A slow/wedged peer's queue drops OLDEST entries once full
    # (counted in gol_peer_send_queue_drops_total) — the retry loop's
    # PEER_PULL re-asks recover anything dropped, so the step loop never
    # blocks and worker memory never grows unboundedly.
    ring_queue_depth: int = 1024
    # Worker-side gather escalation (the reference's gatherer gives up after
    # 2 ask rounds and fires FailedToGatherInfoMsg → neighbor-ref refresh,
    # NextStateCellGathererActor.scala:49-58).  After this many unanswered
    # halo re-pulls a worker reports GATHER_FAILED (keeping its tile and
    # retrying); the frontend then redeploys any blocking neighbor tile that
    # has pushed no ring for stuck_timeout_s — a worker that is alive at the
    # protocol level but wedged in compute, which heartbeats cannot catch.
    max_pull_retries: int = 10
    stuck_timeout_s: float = 60.0
    # Halo re-pull retry policy (the gatherer's 1 s Retry timer,
    # NextStateCellGathererActor.scala:28 — hardened): retry_s is the BASE
    # interval; consecutive unanswered retries of the same tile back off
    # exponentially with decorrelated jitter up to retry_max_s, so a
    # lossy/partitioned link sees a handful of probes per cooling window
    # instead of a fixed-rate re-ask storm.  Frontend-owned cluster policy:
    # shipped to every worker in WELCOME (the constructor default is only
    # the standalone fallback).
    retry_s: float = 0.5
    retry_max_s: float = 8.0
    # Per-peer circuit breaker on the worker data plane: after
    # breaker_failures CONSECUTIVE send failures to one peer the breaker
    # opens (sends to that peer are skipped instead of burning the hot path
    # on connect timeouts); after breaker_cooldown_s it half-opens and lets
    # one probe through — success closes it, failure re-opens.
    breaker_failures: int = 3
    breaker_cooldown_s: float = 2.0
    # -- elastic rebalancing (docs/OPERATIONS.md "Elastic rebalancing") --
    # The frontend's live tile-migration plane: a tile freezes at a chunk
    # boundary on its current owner, its packed state + digest lanes ship
    # through the control plane, the frontend certifies the digest on
    # arrival, and an atomic OWNERS rewiring commits the move (any failure
    # — mismatch, deadline, member loss — rolls back to the source, which
    # never dropped the tile).  Graceful drain (a worker handing its tiles
    # back before leaving) always uses this machinery; rebalance_enabled
    # additionally turns on AUTOMATIC load-driven planning in the frontend
    # maintenance loop.  Every field maps to a --rebalance-* flag
    # (tools/check_rebalance_config.py lint-enforces the bijection).
    rebalance_enabled: bool = False
    # How often the automatic planner looks for imbalance (drain-driven
    # moves ignore this and plan every maintenance pass).
    rebalance_interval_s: float = 1.0
    # Plan a migration when the most- and least-loaded placeable members
    # differ by at least this many tiles.  The planner floors this at 2
    # regardless: a gap-1 move swaps which member is fuller without
    # lowering the peak load, so honoring it would ping-pong one tile
    # forever.  Raise it to tolerate more skew before reshaping.
    rebalance_min_gap: int = 2
    # Concurrent in-flight migrations; each freezes one tile, so a small
    # bound keeps the epoch floor moving while the cluster reshapes.
    rebalance_max_inflight: int = 1
    # Per-migration deadline (PREPARE to certified state arrival); an
    # overdue migration aborts and the source resumes stepping.  Failed
    # migrations retry under the retry_s/retry_max_s decorrelated-jitter
    # backoff policy below.
    rebalance_deadline_s: float = 10.0
    # -- multi-tenant serving plane (docs/OPERATIONS.md "Serving plane") --
    # The serve role's admission-control and batched-engine knobs.  Every
    # field maps to a --serve-X flag (tools/check_serve_config.py
    # lint-enforces the bijection).  Session-count cap per process:
    serve_max_sessions: int = 1024
    # Aggregate live-cell budget across every session — the batch-memory
    # resource a count cap alone cannot bound (1024 sessions of 256² is
    # 64 MiB of boards; of 32² it is 1 MiB).
    serve_max_cells: int = 16_777_216
    # Pending step-job bound; a full queue REJECTS new jobs (HTTP 429)
    # instead of dropping queued ones — a queued job's client is already
    # blocked on it.
    serve_queue_depth: int = 4096
    # Per-request epoch bound (one POST /boards/<id>/step may ask at most
    # this many generations; the scan length buckets to powers of two up
    # to it).
    serve_max_steps: int = 1024
    # Engine pacing floor: at most one batched device program per tick_s
    # (0 = run as fast as jobs arrive — the free-running default, like
    # tick_s for the simulation loop).
    serve_tick_s: float = 0.0
    # Idle-session TTL: a session untouched (no step/get) this long is
    # evicted by the ticker's sweep (0 = never evict).
    serve_ttl_s: float = 300.0
    # Padded size classes (square sides, strictly ascending): a (h, w)
    # board occupies the smallest class ≥ max(h, w), so mixed shapes
    # bucket into a handful of compiled programs; boards beyond the
    # largest class are refused with 400.
    serve_size_classes: str = "32,64,128,256"
    # Cluster-sharded serving (docs/OPERATIONS.md "Serving plane"): fuse
    # the serving plane with the elastic cluster — the frontend becomes
    # the tenant-facing session router, sessions hash-shard across the
    # joined workers (each running its own vmapped batch engine), the
    # rebalancer migrates session shards under load and drain, and a
    # board above the largest size class is admitted as a tiled session
    # instead of being refused.  serve_max_* then bound the CLUSTER, not
    # one process (workers keep the same values as their local backstop).
    serve_cluster: bool = False
    # Virtual session shards — the unit of placement and migration.
    # Sessions hash onto shards (crc32 of the id), shards map onto
    # workers; more shards = finer rebalancing granularity.
    serve_shards: int = 64
    # Epochs per fan-out round of a *tiled* (mega-board) session step:
    # each tile ships with a serve_tile_chunk-wide halo and advances that
    # many epochs per round trip — the exchange-width trade, serve-plane
    # edition (bigger = fewer round trips, fatter halos).
    serve_tile_chunk: int = 8
    # Worker-resident tiled sessions (docs/OPERATIONS.md "Tiled
    # (mega-board) sessions"): a tiled session's chunks are installed ONCE
    # on their assigned workers and stay resident across steps; per-round
    # traffic drops from O(chunk area) through the frontend to O(chunk
    # perimeter) halo strips exchanged worker-to-worker (TILED_HALO
    # frames), with the frontend orchestrating only epoch barriers and
    # digest-lane merges.  Off = the PR 13 ship-per-round path (the board
    # stays frontend-resident and every round ships full chunk state).
    serve_tiled_resident: bool = True
    # Snapshot cadence in ROUNDS (each round = serve_tile_chunk epochs):
    # every Nth barrier each resident chunk retains a local snapshot copy
    # and streams it to its replica — the certified resume point a worker
    # loss rolls the whole session back to.
    serve_tiled_resident_snapshot: int = 4
    # Peer halo strips unacked past this bound retransmit (the loss-
    # recovery half of the tiled_halo/tiled_halo_ack exchange).
    serve_tiled_resident_halo_timeout_s: float = 1.0
    # Session replication & crash failover (docs/OPERATIONS.md "Session
    # replication & failover"): each session shard gets a replica worker
    # (never the primary); the primary streams shard state to it at the
    # cadence below, and on worker loss the frontend PROMOTES the replica
    # instead of 404ing — sessions resume from their last acked
    # replicated epoch, digest-certified.  Off = the PR 13 single-copy
    # plane (a crashed worker's boards 404 honestly).
    serve_replicate: bool = True
    # Epoch cadence: a session re-streams to its replica once it has
    # advanced this many epochs past the acked watermark (new sessions
    # and idle dirty sessions flush regardless — convergence is exact
    # once traffic stops, the cadence only batches under sustained load).
    serve_replicate_every: int = 8
    # The primary's stream-pass interval (how often dirty sessions are
    # exported and shipped; also paces watermark retransmit on loss).
    serve_replicate_interval_s: float = 0.25
    # Replication lag past this bound is surfaced LOUDLY (event + the
    # /healthz lag_alert_shards field) — never silently unbounded.
    serve_replicate_max_lag_s: float = 30.0
    # Serve-plane observability (docs/OPERATIONS.md "Serve observability &
    # SLOs"): request tracing, per-tenant SLO accounting, canary probing.
    # Every field maps to a --serve-X flag (graftlint GL-CFG10 enforces
    # the bijection).  serve_trace: mint/adopt a trace id per HTTP request
    # and ride it through every serve_ops/serve_result/shard_*/replicate/
    # tiled_* frame the request causes, so /trace shows serve.request →
    # worker serve.batch per round.  Off drops the per-request span mint
    # AND the wire propagation (the engine-level serve.tick spans stay).
    serve_trace: bool = True
    # Structured JSONL access-log path ("" = no access log; the /slo
    # summary and RED metrics run regardless).  One line per request:
    # trace id, tenant, route, sid, outcome, queue-wait, latency.
    serve_slo_log: str = ""
    # Availability objective (good requests / all requests) the burn-rate
    # tracker scores against, e.g. 0.999 = "three nines".
    serve_slo_availability: float = 0.999
    # Latency objective: a request slower than this is an SLO-bad request
    # for the latency objective (availability counts only 5xx/timeouts;
    # 429 backpressure is a correct answer, not a burn).
    serve_slo_latency_ms: float = 250.0
    # Multi-window burn-rate windows (fast catches a cliff, slow confirms
    # a sustained burn; the alert fires only when BOTH windows burn past
    # their thresholds — the standard multiwindow page discipline).
    serve_slo_fast_window_s: float = 300.0
    serve_slo_slow_window_s: float = 3600.0
    # Per-tenant label-cardinality cap: beyond this many live tenants the
    # least-recently-seen tenant's series are reclaimed (the PR 7
    # remove() hygiene) and its traffic folds into tenant="~overflow".
    serve_slo_max_tenants: int = 64
    # Canary prober (serve/canary.py): a background synthetic tenant pins
    # one small known-orbit session per worker (the sid= override aims
    # the crc32 shard hash), steps it at cadence through the REAL HTTP
    # surface, and digest-certifies each answer against a precomputed
    # oracle trajectory — silent corruption or a wedged worker becomes a
    # paged gol_canary_* signal within one cadence.
    serve_canary: bool = False
    # Probe cadence (each round steps every pinned canary session once).
    serve_canary_interval_s: float = 2.0
    # Canary board side (square); small on purpose — the probe prices the
    # serving path, not device throughput.
    serve_canary_side: int = 32
    # Cross-tenant memoized macro-stepping (serve/memo.py, docs/
    # OPERATIONS.md "Macro-step memoization"): content-addressed
    # (rule, block) → center-after-S-epochs cache shared across every
    # session of the process — the Hashlife-grade fast path for the
    # nonlinear rules fast-forward cannot touch.  Off by default: the
    # memo plane pays per-tick hashing for cache hits, a trade only
    # repetitive traffic wins.
    serve_memo: bool = False
    # Context block side B (power of two, >= 16): result tiles are B/2,
    # each macro-round advances B/4 epochs.  Bigger blocks amortize more
    # epochs per hit but hash more bytes and repeat less often.
    serve_memo_block: int = 64
    # Cache byte budget (MiB) across all sessions; LRU beyond it.
    serve_memo_max_mb: int = 256
    # Per-session adaptive gate: after warmup, a macro-round whose tile
    # hit rate falls below this floor aborts the task to the dense path
    # (misses unpaid — hashing is the only cost a hostile board forces).
    serve_memo_hit_floor: float = 0.25
    # Ungated probe rounds per session before the floor applies (a cold
    # cache misses everything; warmup is what populates it).
    serve_memo_warmup: int = 16
    # Consecutive below-floor rounds that disable memoization for the
    # session outright (it re-enters only by session recreation).
    serve_memo_disable_after: int = 3
    # Sampled certification cadence: every Nth macro-round of a session
    # (and always its first) is ALSO advanced by the dense batched kernel
    # and digest-compared (gol_memo_certify_*).  0 disables sampling —
    # benchmark configs only; production keeps a nonzero cadence.
    serve_memo_certify_every: int = 64
    # -- frontend federation (docs/OPERATIONS.md "Frontend scale-out &
    # HA"): N frontend processes behind ordinary HTTP load balancing, each
    # owning a rendezvous-hashed slice of the serve shard space, with no
    # coordinator.  Every field maps to a --frontend-* flag (graftlint
    # GL-CFG13 enforces the bijection).  frontend_seeds is the master
    # switch: comma-separated host:port PEER-plane addresses of any live
    # frontends (Akka Cluster seed-nodes, application.conf:7-12); a node
    # seeds itself harmlessly.  "" = federation off (single frontend).
    frontend_seeds: str = ""
    # Advertised peer address as host:port ("" = the bound host and an
    # ephemeral peer port — fine on one machine; multi-host deployments
    # set the externally reachable address).
    frontend_advertise: str = ""
    # Gossip cadence: each tick sends membership + slice-table deltas +
    # budget shares to every live peer and re-dials lost ones.
    frontend_gossip_interval_s: float = 0.5
    # Heartbeat age past which a peer is SUSPECT: its slices are contested
    # — writes park with retryable 429 — until the link actually closes
    # (confirmed death → promotion) or gossip resumes (flap → no-op).
    # This asymmetry is the split-brain guard: silence alone never
    # transfers ownership.
    frontend_gossip_timeout_s: float = 3.0
    # Control-state replication to the slice's standby peer: flush the
    # dirty-row buffer once it holds this many rows (the interval flushes
    # any dirty remainder regardless, so convergence is exact once
    # traffic stops).
    frontend_replicate_every: int = 16
    # The dirty-row stream pass cadence (also paces ack-watermark
    # retransmit after a peer reconnect).
    frontend_replicate_interval_s: float = 0.25
    # -- logarithmic fast-forward (docs/OPERATIONS.md "Logarithmic
    # fast-forward").  XOR-linear (odd-rule) boards jump T epochs in
    # O(log T) device programs (ops/fastforward.py); non-linear rules are
    # provably refused, never silently jumped.  Every field maps to a
    # --ff-* flag and a doc knob-table row (graftlint GL-CFG07 + GL-DOC05
    # lint-enforce the CLI ↔ config ↔ operator-doc bijection, two-way).
    # Master switch: Simulation.fast_forward and the serve fast path
    # refuse when off (serve then answers 429 `max_steps` past the bound).
    ff_enabled: bool = True
    # Jump-vs-iterate certification sample: before a jump commits,
    # min(T, this) epochs are ALSO iterated through the ordinary dense
    # stepper and the two digests must agree (RuntimeError on divergence).
    # 0 = skip; the sample costs O(sample · area), so headline-size
    # runbooks time with 0 and certify via a separate anchor jump.
    ff_certify_steps: int = 8
    # -- activity-gated sparse stepping (docs/OPERATIONS.md "Activity-gated
    # sparse stepping").  Two independent tiers that convert throughput from
    # O(area) toward O(activity) on dilute boards; every field maps to a
    # --sparse-* flag (tools/check_sparse_config.py lint-enforces the
    # bijection).
    # sparse_cluster: cluster tier — a tile whose state AND assembled halo
    # are unchanged across a chunk (or match the chunk two back: cheap
    # period-2 detection) is provably quiescent; its worker skips the step
    # compute, publishes an O(1)-byte "same-ring" marker instead of ring
    # payloads, and suppresses per-chunk PROGRESS pings (cadence pings and
    # digest-due certificates still flow).  A changed neighboring ring wakes
    # the tile before its next chunk — zero wrong-state epochs, because the
    # epoch-tagged halo protocol itself is the wake signal.  Frontend-owned
    # policy, shipped to every worker in WELCOME like the ring policy.
    sparse_cluster: bool = False
    # sparse_kernel: intra-tile tier (standalone runs) — a coarse activity
    # bitmap (one bit per sparse_block² cell block, recomputed from each
    # chunk's output) gates which blocks the stepper actually advances: a
    # block steps only if it or a block-ring neighbor changed last chunk
    # (exact for radius-1 rules with steps_per_call <= sparse_block).
    sparse_kernel: bool = False
    # Gating block side in cells (clamped to the largest common divisor of
    # the board sides <= this, so blocks always tile the torus exactly).
    sparse_block: int = 128
    # Dense escape hatch: once the dilated active fraction exceeds this,
    # the chunk steps the whole board through the ordinary dense kernel and
    # only the changed-block bitmap is recomputed — boiling boards pay one
    # O(area) compare per chunk, never a per-block host loop.
    sparse_threshold: float = 0.5
    # Optional deadline on cluster channel sends (seconds; 0 = block
    # forever, the classic TCP behavior).  With a deadline, a send into a
    # wedged peer's full socket buffer raises after this long instead of
    # blocking the sending thread (heartbeats, ring publishes) forever;
    # the channel is then treated as dead (a partial frame may have been
    # written, so it cannot be reused).
    send_deadline_s: float = 0.0

    # Checkpoint / resume (capability the reference lacks — SURVEY.md §5).
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0  # epochs between checkpoints; 0 = disabled
    checkpoint_format: str = "npz"  # "npz" (host) | "orbax" (async, device)
    # Overlap npz checkpoint writes with compute: the save (device fetch +
    # file write) runs on a writer thread while stepping continues, with at
    # most one save in flight (single-process runs only — the multi-host npz
    # path keeps its durability barrier, and orbax is already async).  At
    # the 65536² headline config a save costs ~25 s that would otherwise
    # stall the run.  False = block at each checkpoint (every save durable
    # the moment checkpoint() returns).
    checkpoint_async: bool = True
    # (Boundary-ring history is bounded by the checkpoint-cadence PRUNE
    # floor, not a separate window — see frontend._on_tile_state.)

    # Rendering / observability (LoggerActor capability).
    render_every: int = 0  # epochs between rendered frames; 0 = never
    render_max_cells: int = 128  # stride-sample larger boards down to this
    # An exact-cell probe window (y0, y1, x0, x1) printed at render cadence —
    # the at-scale correctness view (e.g. the Gosper-gun region of a 65536²
    # run), fetched O(window) via Simulation.board_window.  None = off.
    probe_window: Optional[Tuple[int, int, int, int]] = None
    log_file: Optional[str] = None  # reference renders to info.log
    metrics_every: int = 0
    # Metrics exposition (obs/): Prometheus text dumped to this file at
    # metrics cadence and on close (atomic tmp+rename — a scrape never sees
    # a torn write) ...
    metrics_file: Optional[str] = None
    # ... and/or served live at http://host:metrics_port/metrics (+ /healthz)
    # by the run and frontend roles.  0 = no HTTP endpoint.
    metrics_port: int = 0
    # Structured JSONL lifecycle events (crashes, recoveries, checkpoints,
    # membership churn) appended here with monotonic timestamps and a
    # per-node label.  None = off.
    log_events: Optional[str] = None
    # Distributed span tracing (obs/tracing.py): write the run's span buffer
    # here as Chrome trace-event / Perfetto JSON on close.  The span buffer
    # is always recording (bounded); this only controls the file export —
    # the live view is the obs endpoint's /trace.  None = no file.
    trace_file: Optional[str] = None
    # Crash flight recorder (obs/flight.py): directory for the automatic
    # last-N-spans+events dumps written on injected crashes, supervision
    # replays, node-loss redeploys, and SIGTERM.  Empty string disables
    # dumping (the ring still records for /trace continuity).
    flight_dir: str = "artifacts"
    # Deferred observation: cadence points dispatch their device-side
    # observation (population / render sample / probe window) and return
    # without any host fetch; the tiny results are fetched one chunk later,
    # while the device is busy on the next stepper chunk — so the host
    # round-trip (the dominant per-chunk cost over a slow device tunnel)
    # leaves the critical path.  Observer lines for a cadence point are
    # emitted one chunk late; values and totals are identical to sync mode.
    obs_defer: bool = False
    # Digest observation mode (docs/OPERATIONS.md "Digest certification"):
    # cadence observations additionally compute the 64-bit board digest
    # (ops/digest.py) on device and fetch ~8 bytes — state certification
    # without board transfer.  Standalone: the digest rides the cadence
    # observation (and obs_defer's deferred fetch) and prints on the
    # metrics line.  Cluster: workers digest their tiles locally and
    # attach the lanes to PROGRESS pings at metrics/checkpoint/final
    # epochs; the frontend merges them in O(tiles) bytes and records the
    # merged digest in finalized checkpoint metadata.
    obs_digest: bool = False
    # Compile & device-cost observatory (obs/programs.py): the jit-program
    # ledger behind /programs, /cost, compile-storm alerts, and workers'
    # COST frames.  Off makes registered_jit a pass-through for programs
    # built afterward (zero wrapper overhead; the HTTP routes stay mounted
    # and report an empty ledger).
    obs_programs: bool = True
    # Cadence of the worker→frontend COST frames (and of the local
    # device-memory gauge refresh on cluster roles).
    obs_cost_interval_s: float = 5.0
    # POST /profile guard rails: longest admissible capture window, and the
    # minimum gap between captures (429 inside the gap) — the obs port is
    # unauthenticated, so the profiler must not be a DoS lever.
    obs_profile_max_s: float = 30.0
    obs_profile_min_interval_s: float = 60.0

    fault_injection: FaultInjectionConfig = dataclasses.field(
        default_factory=FaultInjectionConfig
    )
    net_chaos: NetworkChaosConfig = dataclasses.field(
        default_factory=NetworkChaosConfig
    )

    def __post_init__(self) -> None:
        if self.height <= 0 or self.width <= 0:
            raise ValueError(f"board must be positive, got {self.height}x{self.width}")
        if self.backend not in ("tpu", "actor", "actor-native"):
            raise ValueError(f"unknown backend {self.backend!r}")
        if self.kernel not in KERNEL_CHOICES:
            raise ValueError(
                f"unknown kernel {self.kernel!r}; choose from {KERNEL_CHOICES}"
            )
        if self.pallas_block_rows < 8 or self.pallas_block_rows % 8:
            # Mosaic requires sublane-dim block sizes in multiples of 8
            # (ops/pallas_stencil.py); catch it here with the knob's name
            # instead of a bare max()/ZeroDivisionError deep in the run.
            raise ValueError(
                f"pallas_block_rows={self.pallas_block_rows} must be a "
                f"positive multiple of 8 (TPU sublane tile)"
            )
        if self.pallas_vmem_limit_mb < 0:
            raise ValueError(
                f"pallas_vmem_limit_mb={self.pallas_vmem_limit_mb} must be >= 0"
            )
        if self.probe_window is not None:
            y0, y1, x0, x1 = self.probe_window
            if not (0 <= y0 < y1 <= self.height and 0 <= x0 < x1 <= self.width):
                raise ValueError(
                    f"probe_window {self.probe_window} out of bounds for "
                    f"{self.height}x{self.width}"
                )
        if self.role not in ("standalone", "frontend", "backend", "serve"):
            raise ValueError(f"unknown role {self.role!r}")
        if not (0 <= self.metrics_port < 65536):
            raise ValueError(
                f"metrics_port={self.metrics_port} must be 0 (off) or a "
                f"valid TCP port"
            )
        if self.obs_cost_interval_s <= 0:
            raise ValueError(
                f"obs_cost_interval_s={self.obs_cost_interval_s} must be > 0"
            )
        if self.obs_profile_max_s <= 0:
            raise ValueError(
                f"obs_profile_max_s={self.obs_profile_max_s} must be > 0"
            )
        if self.obs_profile_min_interval_s < 0:
            raise ValueError(
                f"obs_profile_min_interval_s="
                f"{self.obs_profile_min_interval_s} must be >= 0 (0 = no "
                f"rate limit)"
            )
        if self.checkpoint_format not in ("npz", "orbax"):
            raise ValueError(f"unknown checkpoint format {self.checkpoint_format!r}")
        if self.steps_per_call % self.halo_width:
            raise ValueError("steps_per_call must be a multiple of halo_width")
        if self.retry_s <= 0:
            raise ValueError(f"retry_s={self.retry_s} must be > 0")
        if self.retry_max_s < self.retry_s:
            raise ValueError(
                f"retry_max_s={self.retry_max_s} must be >= retry_s="
                f"{self.retry_s} (it is the backoff cap)"
            )
        if self.breaker_failures < 1:
            raise ValueError(
                f"breaker_failures={self.breaker_failures} must be >= 1"
            )
        if self.breaker_cooldown_s <= 0:
            raise ValueError(
                f"breaker_cooldown_s={self.breaker_cooldown_s} must be > 0"
            )
        if self.send_deadline_s < 0:
            raise ValueError(
                f"send_deadline_s={self.send_deadline_s} must be >= 0 (0 = off)"
            )
        if self.rebalance_interval_s <= 0:
            raise ValueError(
                f"rebalance_interval_s={self.rebalance_interval_s} must be > 0"
            )
        if self.rebalance_min_gap < 1:
            raise ValueError(
                f"rebalance_min_gap={self.rebalance_min_gap} must be >= 1"
            )
        if self.rebalance_max_inflight < 1:
            raise ValueError(
                f"rebalance_max_inflight={self.rebalance_max_inflight} "
                f"must be >= 1"
            )
        if self.rebalance_deadline_s <= 0:
            raise ValueError(
                f"rebalance_deadline_s={self.rebalance_deadline_s} must be > 0"
            )
        if self.tiles_per_worker < 1:
            raise ValueError(
                f"tiles_per_worker must be >= 1, got {self.tiles_per_worker}"
            )
        if self.ring_queue_depth < 1:
            raise ValueError(
                f"ring_queue_depth must be >= 1, got {self.ring_queue_depth}"
            )
        for name in (
            "serve_max_sessions",
            "serve_max_cells",
            "serve_queue_depth",
            "serve_max_steps",
            "serve_shards",
            "serve_tile_chunk",
            "serve_replicate_every",
            "serve_tiled_resident_snapshot",
        ):
            if getattr(self, name) < 1:
                raise ValueError(
                    f"{name}={getattr(self, name)} must be >= 1"
                )
        if self.serve_tiled_resident_halo_timeout_s <= 0:
            raise ValueError(
                f"serve_tiled_resident_halo_timeout_s="
                f"{self.serve_tiled_resident_halo_timeout_s} must be > 0"
            )
        if self.serve_replicate_interval_s <= 0:
            raise ValueError(
                f"serve_replicate_interval_s="
                f"{self.serve_replicate_interval_s} must be > 0"
            )
        if self.serve_replicate_max_lag_s <= 0:
            raise ValueError(
                f"serve_replicate_max_lag_s="
                f"{self.serve_replicate_max_lag_s} must be > 0"
            )
        if self.serve_tick_s < 0:
            raise ValueError(
                f"serve_tick_s={self.serve_tick_s} must be >= 0 (0 = "
                f"free-running)"
            )
        if self.serve_ttl_s < 0:
            raise ValueError(
                f"serve_ttl_s={self.serve_ttl_s} must be >= 0 (0 = never "
                f"evict)"
            )
        parse_size_classes(self.serve_size_classes)
        if not 0.0 < self.serve_slo_availability < 1.0:
            raise ValueError(
                f"serve_slo_availability={self.serve_slo_availability} "
                f"must be in (0, 1)"
            )
        if self.serve_slo_latency_ms <= 0:
            raise ValueError(
                f"serve_slo_latency_ms={self.serve_slo_latency_ms} must "
                f"be > 0"
            )
        if self.serve_slo_fast_window_s <= 0:
            raise ValueError(
                f"serve_slo_fast_window_s={self.serve_slo_fast_window_s} "
                f"must be > 0"
            )
        if self.serve_slo_slow_window_s < self.serve_slo_fast_window_s:
            raise ValueError(
                f"serve_slo_slow_window_s={self.serve_slo_slow_window_s} "
                f"must be >= serve_slo_fast_window_s="
                f"{self.serve_slo_fast_window_s}"
            )
        if self.serve_slo_max_tenants < 1:
            raise ValueError(
                f"serve_slo_max_tenants={self.serve_slo_max_tenants} "
                f"must be >= 1"
            )
        if self.serve_canary_interval_s <= 0:
            raise ValueError(
                f"serve_canary_interval_s={self.serve_canary_interval_s} "
                f"must be > 0"
            )
        if self.serve_canary_side < 1:
            raise ValueError(
                f"serve_canary_side={self.serve_canary_side} must be >= 1"
            )
        from akka_game_of_life_tpu.ops.macroblock import MIN_BLOCK

        if (
            self.serve_memo_block < MIN_BLOCK
            or self.serve_memo_block & (self.serve_memo_block - 1) != 0
        ):
            raise ValueError(
                f"serve_memo_block={self.serve_memo_block} must be a "
                f"power of two >= {MIN_BLOCK} (the macro-cell theorem "
                f"needs B/4 halo epochs)"
            )
        if self.serve_memo_max_mb < 1:
            raise ValueError(
                f"serve_memo_max_mb={self.serve_memo_max_mb} must be >= 1"
            )
        if not 0.0 <= self.serve_memo_hit_floor <= 1.0:
            raise ValueError(
                f"serve_memo_hit_floor={self.serve_memo_hit_floor} must "
                f"be in [0, 1]"
            )
        if self.serve_memo_warmup < 0:
            raise ValueError(
                f"serve_memo_warmup={self.serve_memo_warmup} must be >= 0"
            )
        if self.serve_memo_disable_after < 1:
            raise ValueError(
                f"serve_memo_disable_after={self.serve_memo_disable_after} "
                f"must be >= 1"
            )
        if self.serve_memo_certify_every < 0:
            raise ValueError(
                f"serve_memo_certify_every={self.serve_memo_certify_every} "
                f"must be >= 0 (0 = no sampled certification)"
            )
        for name in ("frontend_seeds", "frontend_advertise"):
            value = getattr(self, name)
            entries = [s for s in value.split(",") if s.strip()]
            if name == "frontend_advertise" and len(entries) > 1:
                raise ValueError(
                    f"frontend_advertise={value!r} must be one host:port"
                )
            for entry in entries:
                host, sep, port = entry.strip().rpartition(":")
                if not sep or not host or not port.isdigit():
                    raise ValueError(
                        f"{name} entry {entry.strip()!r} must be host:port"
                    )
        if self.frontend_gossip_interval_s <= 0:
            raise ValueError(
                f"frontend_gossip_interval_s="
                f"{self.frontend_gossip_interval_s} must be > 0"
            )
        if self.frontend_gossip_timeout_s <= self.frontend_gossip_interval_s:
            raise ValueError(
                f"frontend_gossip_timeout_s="
                f"{self.frontend_gossip_timeout_s} must exceed "
                f"frontend_gossip_interval_s="
                f"{self.frontend_gossip_interval_s} (a peer must miss "
                f"multiple gossip ticks before it is suspect)"
            )
        if self.frontend_replicate_every < 1:
            raise ValueError(
                f"frontend_replicate_every={self.frontend_replicate_every} "
                f"must be >= 1"
            )
        if self.frontend_replicate_interval_s <= 0:
            raise ValueError(
                f"frontend_replicate_interval_s="
                f"{self.frontend_replicate_interval_s} must be > 0"
            )
        if self.ff_certify_steps < 0:
            raise ValueError(
                f"ff_certify_steps={self.ff_certify_steps} must be >= 0 "
                f"(0 = skip jump-vs-iterate certification)"
            )
        if self.sparse_block < 1:
            raise ValueError(
                f"sparse_block={self.sparse_block} must be >= 1"
            )
        if not 0.0 <= self.sparse_threshold <= 1.0:
            raise ValueError(
                f"sparse_threshold={self.sparse_threshold} must be in [0, 1]"
            )
        if self.exchange_width < 1:
            raise ValueError(f"exchange_width must be >= 1, got {self.exchange_width}")
        if self.exchange_width > 1:
            for name in ("render_every", "metrics_every", "checkpoint_every"):
                cadence = getattr(self, name)
                if cadence and cadence % self.exchange_width:
                    raise ValueError(
                        f"{name}={cadence} must be a multiple of "
                        f"exchange_width={self.exchange_width}: cluster tiles "
                        f"advance in exchange_width-epoch chunks, so other "
                        f"epochs are never observable"
                    )

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.height, self.width)

    @property
    def pallas_vmem_limit_bytes(self) -> Optional[int]:
        """The Mosaic VMEM budget in bytes, or None for the compiler default."""
        return self.pallas_vmem_limit_mb * 2**20 or None


_DURATION_FIELDS = {
    "wait_for_backends_s",
    "start_delay_s",
    "tick_s",
    "heartbeat_s",
    "failure_timeout_s",
    "restart_window_s",
    "stuck_timeout_s",
    "first_after_s",
    "every_s",
    "retry_s",
    "retry_max_s",
    "rebalance_interval_s",
    "rebalance_deadline_s",
    "serve_tick_s",
    "serve_ttl_s",
    "serve_replicate_interval_s",
    "serve_replicate_max_lag_s",
    "frontend_gossip_interval_s",
    "frontend_gossip_timeout_s",
    "frontend_replicate_interval_s",
    "serve_tiled_resident_halo_timeout_s",
    "serve_slo_fast_window_s",
    "serve_slo_slow_window_s",
    "serve_canary_interval_s",
    "obs_cost_interval_s",
    "obs_profile_max_s",
    "obs_profile_min_interval_s",
    "breaker_cooldown_s",
    "send_deadline_s",
    "delay_s",
    "partition_after_s",
    "partition_every_s",
    "partition_heal_s",
}

# Accept the reference's config spellings as aliases.
_ALIASES = {
    "x": "width",
    "y": "height",
    "wait-for-backends": "wait_for_backends_s",
    "start-delay": "start_delay_s",
    "tick": "tick_s",
    "max-crashes": "max_crashes",
    "delay": "first_after_s",
    "every": "every_s",
}


def _normalize(data: Mapping[str, Any], *, nested: bool = False) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, value in data.items():
        key = _ALIASES.get(key, key.replace("-", "_"))
        if key == "max_crashes" and not nested:
            # The reference keeps max-crashes at the game-of-life level
            # (application.conf:41) but it belongs to the fault injector.
            out.setdefault("fault_injection", {})["max_crashes"] = value
            continue
        if isinstance(value, Mapping) and key not in (
            "fault_injection",
            "net_chaos",
        ):
            # Flatten one nesting level (e.g. the reference's board {x, y} /
            # error {delay, every} sub-blocks).
            if key in ("board", "game_of_life"):
                out.update(_normalize(value))
                continue
            if key == "error":
                fi = out.setdefault("fault_injection", {})
                fi.update(_normalize(value, nested=True))
                continue
        if key in ("fault_injection", "net_chaos") and isinstance(value, Mapping):
            out.setdefault(key, {}).update(_normalize(value, nested=True))
            continue
        if key in _DURATION_FIELDS and value is not None:
            value = parse_duration(value)
        out[key] = value
    return out


def _field_names(cls) -> set:
    return {f.name for f in dataclasses.fields(cls)}


def load_config(
    path: Optional[str] = None,
    overrides: Optional[Mapping[str, Any]] = None,
) -> SimulationConfig:
    """Build a config with layered precedence: defaults < file < overrides.

    ``path`` may be TOML or JSON.  Unknown keys are rejected so typos fail
    loudly instead of silently running defaults.
    """
    merged: Dict[str, Any] = {}
    if path is not None:
        p = Path(path)
        text = p.read_text()
        if p.suffix == ".json":
            data = json.loads(text)
        else:
            try:
                import tomllib  # Python >= 3.11
            except ModuleNotFoundError:  # 3.10: same API under the old name
                import tomli as tomllib

            data = tomllib.loads(text)
        merged.update(_normalize(data))
    if overrides:
        deep = _normalize({k: v for k, v in overrides.items() if v is not None})
        fi = {**merged.get("fault_injection", {}), **deep.pop("fault_injection", {})}
        nc = {**merged.get("net_chaos", {}), **deep.pop("net_chaos", {})}
        merged.update(deep)
        if fi:
            merged["fault_injection"] = fi
        if nc:
            merged["net_chaos"] = nc

    fi_kwargs = merged.pop("fault_injection", {})
    nc_kwargs = merged.pop("net_chaos", {})
    unknown = set(merged) - _field_names(SimulationConfig)
    unknown_fi = set(fi_kwargs) - _field_names(FaultInjectionConfig)
    unknown_nc = set(nc_kwargs) - _field_names(NetworkChaosConfig)
    if unknown or unknown_fi or unknown_nc:
        raise ValueError(
            f"unknown config keys: {sorted(unknown | unknown_fi | unknown_nc)}"
        )

    if "mesh_shape" in merged and merged["mesh_shape"] is not None:
        merged["mesh_shape"] = tuple(merged["mesh_shape"])
    if "pattern_offset" in merged:
        merged["pattern_offset"] = tuple(merged["pattern_offset"])
    if "probe_window" in merged and merged["probe_window"] is not None:
        merged["probe_window"] = tuple(merged["probe_window"])
    return SimulationConfig(
        fault_injection=FaultInjectionConfig(**fi_kwargs),
        net_chaos=NetworkChaosConfig(**nc_kwargs),
        **merged,
    )
