"""In-process cluster harness: frontend + N backend workers as threads.

One process standing in for the reference's "start N backend JVMs on
localhost" manual procedure (``README.md:3-12``) — used by the test suite
(trajectory equivalence, chaos drills), by ``bench_suite.py``'s
cluster-exchange config, and available to library users who want a local
cluster without shell plumbing.  Real multi-process clusters use the CLI
roles (``python -m akka_game_of_life_tpu frontend/backend``).
"""

from __future__ import annotations

import contextlib
import threading

from akka_game_of_life_tpu.runtime.backend import BackendWorker
from akka_game_of_life_tpu.runtime.frontend import Frontend

DONE_TIMEOUT = 60


class ClusterHarness:
    def __init__(
        self,
        config,
        n_backends,
        observer=None,
        engine="numpy",
        pallas=None,
        registry=None,
        tracer=None,
    ):
        # numpy engine keeps test suites fast and portable; pass engine="jax"
        # (or "swar") for the accelerator/native data paths; pallas pins the
        # jax engine's Mosaic mode (see BackendWorker).  registry/tracer
        # isolate the whole cluster's metrics and spans (tests assert
        # counters and causal trees without cross-test bleed); None = the
        # process defaults.  With one shared tracer the frontend's epoch
        # span and every worker's step/halo spans land in one buffer — the
        # in-process analog of merging per-process trace files.  When
        # config.net_chaos is enabled, the frontend's NetworkChaos instance
        # is shared by every worker, so partition sides and the seeded
        # fault stream are consistent cluster-wide (netchaos attribute).
        self.engine = engine
        self.pallas = pallas
        self.registry = registry
        self.tracer = tracer
        config.port = 0  # ephemeral: parallel harnesses must not fight over 2551
        self.frontend = Frontend(
            config,
            min_backends=n_backends,
            observer=observer,
            registry=registry,
            tracer=tracer,
        )
        self.frontend.start()
        self.netchaos = self.frontend.netchaos
        self.workers = []
        self.threads = []
        for i in range(n_backends):
            self.add_worker(f"w{i}")

    def add_worker(self, name):
        # No retry/breaker knobs here: WELCOME ships the frontend's
        # SimulationConfig policy (retry_s, retry_max_s, breaker_*,
        # send_deadline_s), so tests and CLI share one source of truth.
        w = BackendWorker(
            "127.0.0.1",
            self.frontend.port,
            name=name,
            engine=self.engine,
            pallas=self.pallas,
            registry=self.registry,
            tracer=self.tracer,
            netchaos=self.frontend.netchaos,
        )
        w.crash_hook = w.stop  # in-thread "process death": drop the connection
        w.connect()
        t = threading.Thread(target=w.run, daemon=True, name=f"worker-{name}")
        t.start()
        self.workers.append(w)
        self.threads.append(t)
        return w

    def drain_worker(self, w, timeout: float = 30.0) -> str:
        """Gracefully drain one in-process worker: request the drain and
        wait for its serve thread to exit (the frontend live-migrates its
        tiles off, then releases it).  Returns the worker's stopped_reason
        — "drained" on success."""
        assert w.request_drain(), "drain request not sendable"
        t = self.threads[self.workers.index(w)]
        t.join(timeout)
        return w.stopped_reason

    def run_to_completion(self, timeout: float = DONE_TIMEOUT):
        assert self.frontend.wait_for_backends(timeout=5)
        self.frontend.start_simulation()
        assert self.frontend.done.wait(timeout), "cluster did not finish"
        assert self.frontend.error is None, self.frontend.error
        return self.frontend.final_board

    def shutdown(self):
        self.frontend.stop()
        for w in self.workers:
            w.stop()


@contextlib.contextmanager
def cluster(
    config,
    n_backends,
    observer=None,
    engine="numpy",
    pallas=None,
    registry=None,
    tracer=None,
):
    h = ClusterHarness(
        config,
        n_backends,
        observer=observer,
        engine=engine,
        pallas=pallas,
        registry=registry,
        tracer=tracer,
    )
    try:
        yield h
    finally:
        h.shutdown()
