"""Persistent XLA compilation cache for every entry point.

First jit compile of the 65536² kernels costs 20-40 s through the axon
tunnel — often the dominant cost of a short measurement window on this
image, where the tunnel serves ~10-minute alive windows between
multi-hour wedges (artifacts/tpu_session_r4/OUTAGE.md).  JAX's
persistent cache turns every re-compile of an already-seen program into
a disk load, across processes, so repeat runs (bench re-runs, tune
sweeps revisiting a config, product restarts from checkpoints) skip the
tunnel compile entirely.

Enabled by every CLI subcommand and bench entry point; the reference has
no analog (JVM actors have no compile step — parity-neutral, pure
operational win).  Failure-proof by construction: a PJRT plugin without
executable (de)serialization support degrades to JAX's own warning and
a normal compile, and any error enabling the cache is swallowed — a
broken cache must never break a run.

``GOL_COMPILE_CACHE=0`` disables; ``GOL_COMPILE_CACHE_DIR`` overrides
the default repo-local ``.jax_cache`` directory (git-ignored).
CPU-pinned runs (``--platform cpu`` / ``GOL_PLATFORM=cpu``, or any
cpu-first platform list) skip the cache regardless: host compiles are
fast, and XLA:CPU's AOT loader warns ("could lead to SIGILL") on every
cache hit — the cache exists for slow *device* compiles.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    ".jax_cache",
)


def enable_compile_cache() -> str | None:
    """Turn on JAX's persistent compilation cache; returns the cache dir
    actually enabled, or None if disabled/unavailable."""
    if os.environ.get("GOL_COMPILE_CACHE", "1").strip().lower() in (
        "0",
        "false",
        "off",
        "no",
    ):
        return None
    cache_dir = os.environ.get("GOL_COMPILE_CACHE_DIR", _DEFAULT_DIR)
    try:
        import jax

        # CPU-pinned runs skip the cache: host compiles are fast (the cache
        # exists for 20-40 s device-tunnel compiles), and XLA:CPU's AOT
        # loader warns about machine-feature fingerprints on every cache
        # hit ("could lead to SIGILL") — noise and theoretical risk for no
        # benefit.  Checked via the *configured* platform string only
        # (first element of a priority list like "cpu,axon"): calling
        # jax.default_backend() here would initialize the backend, which
        # HANGS on a wedged device tunnel.
        platforms = jax.config.jax_platforms or ""
        if platforms.split(",")[0].strip() == "cpu":
            return None
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # Cache every compile that costs >= 1 s: the tunnel compiles we
        # care about cost tens of seconds; sub-second host compiles are
        # not worth the disk churn.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return cache_dir
    except Exception:  # noqa: BLE001 — cache is an optimization, never a failure
        return None
