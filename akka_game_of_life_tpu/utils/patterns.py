"""Pattern library: RLE decoding, canonical Life patterns, random boards.

The reference has no pattern machinery at all — its only initial condition is
a Bernoulli(1/2) random board (``BoardCreator.scala:23,47-53``).  Patterns are
needed here because the framework's correctness north star (BASELINE.json) is
*pattern-level*: blinker period 2, glider translation, Gosper glider-gun
period 30 preserved across backend kill/restart.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

import numpy as np

# Run-length-encoded patterns in the standard Golly/LifeWiki RLE format.
# `b` = dead, `o` = alive, `$` = end of row, `!` = end of pattern.
RLE_PATTERNS: Dict[str, str] = {
    "blinker": "3o!",
    "block": "2o$2o!",
    "beehive": "b2o$o2bo$b2o!",
    "toad": "b3o$3o!",
    "beacon": "2o$2o$2b2o$2b2o!",
    "glider": "bob$2bo$3o!",
    "lwss": "b4o$o3bo$4bo$o2bo!",
    "pulsar": (
        "2b3o3b3o2b$13b$o4bobo4bo$o4bobo4bo$o4bobo4bo$2b3o3b3o2b$13b"
        "$2b3o3b3o2b$o4bobo4bo$o4bobo4bo$o4bobo4bo$13b$2b3o3b3o2b!"
    ),
    "r-pentomino": "b2o$2o$bo!",
    "pentadecathlon": "2bo4bo$2ob4ob2o$2bo4bo!",  # period-15 oscillator
    "diehard": "6bob$2o6b$bo3b3o!",  # vanishes after exactly 130 generations
    "acorn": "bo5b$3bo3b$2o2b3o!",  # 5206-gen methuselah (pop 633 stable)
    "gosper-glider-gun": (
        "24bo$22bobo$12b2o6b2o12b2o$11bo3bo4b2o12b2o$2o8bo5bo3b2o$2o8bo3bob2o4b"
        "obo$10bo5bo7bo$11bo3bo$12b2o!"
    ),
    # HighLife replicator (B36/S23).
    "replicator": "2b3o$bo2bo$o3bo$o2bob$3o!",
}

_RLE_TOKEN = re.compile(r"(\d*)([bo$!])")


def decode_rle(rle: str) -> np.ndarray:
    """Decode an RLE body string into a (H, W) uint8 0/1 array."""
    rows = []
    row = []
    for count_s, tag in _RLE_TOKEN.findall(rle.replace("\n", "").replace(" ", "")):
        count = int(count_s) if count_s else 1
        if tag == "b":
            row.extend([0] * count)
        elif tag == "o":
            row.extend([1] * count)
        elif tag == "$":
            rows.append(row)
            # A multi-count `$` encodes blank rows.
            rows.extend([[]] * (count - 1))
            row = []
        elif tag == "!":
            rows.append(row)
            row = []
            break
    if row:
        # Tolerate a missing '!' terminator (truncated paste) rather than
        # silently dropping the final row.
        rows.append(row)
    width = max((len(r) for r in rows), default=0)
    grid = np.zeros((len(rows), width), dtype=np.uint8)
    for y, r in enumerate(rows):
        grid[y, : len(r)] = r
    return grid


# Multi-state patterns (state digits), for families RLE's b/o can't encode.
# Wireworld states: 0 empty, 1 electron head, 2 tail, 3 conductor.
DIGIT_PATTERNS: Dict[str, Tuple[str, ...]] = {
    # A 10-cell octagonal wire loop (corners cut so every path cell has
    # exactly 2 path neighbors — square corners would double the electron
    # through Moore diagonals) with one electron circulating: period 10.
    "wireworld-clock": (
        "02330",
        "10003",
        "30003",
        "03330",
    ),
}


def get_pattern(name: str) -> np.ndarray:
    """Look up a canonical pattern by name as a (H, W) uint8 array."""
    key = name.strip().lower()
    if key in DIGIT_PATTERNS:
        return np.array(
            [[int(ch) for ch in row] for row in DIGIT_PATTERNS[key]],
            dtype=np.uint8,
        )
    if key not in RLE_PATTERNS:
        raise KeyError(
            f"unknown pattern {name!r}; have "
            f"{sorted(RLE_PATTERNS) + sorted(DIGIT_PATTERNS)}"
        )
    return decode_rle(RLE_PATTERNS[key])


def place(
    board: np.ndarray, pattern: np.ndarray, top_left: Tuple[int, int] = (0, 0)
) -> np.ndarray:
    """Return a copy of ``board`` with ``pattern`` stamped at ``top_left``
    (toroidal wrap if the pattern crosses the board edge)."""
    out = np.array(board, copy=True)
    h, w = out.shape
    py, px = pattern.shape
    if py > h or px > w:
        raise ValueError(
            f"pattern {pattern.shape} does not fit board {board.shape}"
        )
    y0, x0 = top_left
    ys = (np.arange(py) + y0) % h
    xs = (np.arange(px) + x0) % w
    out[np.ix_(ys, xs)] = pattern
    return out


def pattern_board(
    name: str, board_shape: Tuple[int, int], top_left: Tuple[int, int] = (0, 0)
) -> np.ndarray:
    """An empty (H, W) uint8 torus with a named pattern stamped on it."""
    board = np.zeros(board_shape, dtype=np.uint8)
    return place(board, get_pattern(name), top_left)


def random_grid(
    shape: Tuple[int, int],
    *,
    density: float = 0.5,
    seed: int = 0,
    states: int = 2,
) -> np.ndarray:
    """Random initial board — the reference's Bernoulli(1/2) initial state
    (``BoardCreator.scala:23``), generalized to a density knob and, for
    Generations rules, to live-state-only randomness (refractory states are
    never part of a fresh board)."""
    del states  # live/dead only; refractory states arise from dynamics
    rng = np.random.default_rng(seed)
    # Chunked uint16 thresholding: rng.random would allocate 8 bytes/cell
    # (34 GiB at 65536²); this path peaks at the uint8 board plus one
    # ~256 MiB scratch block, with density quantized to 1/65536.
    h, w = shape
    thresh = max(0, min(65536, round(density * 65536)))
    # Saturated densities never reach the comparison: 65536 overflows uint16
    # (np.less with an out-of-range python int segfaults NumPy 2.0.2).
    if thresh == 0:
        return np.zeros(shape, dtype=np.uint8)
    if thresh == 65536:
        return np.ones(shape, dtype=np.uint8)
    out = np.empty(shape, dtype=np.uint8)
    t16 = np.uint16(thresh)
    rows_per = max(1, (1 << 27) // max(1, w))
    for y in range(0, h, rows_per):
        block = rng.integers(
            0, 65536, size=(min(rows_per, h - y), w), dtype=np.uint16
        )
        np.less(block, t16, out=out[y : y + block.shape[0]])
    return out
