"""Pattern library: RLE codec, canonical Life patterns, random boards.

The reference has no pattern machinery at all — its only initial condition is
a Bernoulli(1/2) random board (``BoardCreator.scala:23,47-53``).  Patterns are
needed here because the framework's correctness north star (BASELINE.json) is
*pattern-level*: blinker period 2, glider translation, Gosper glider-gun
period 30 preserved across backend kill/restart.

Beyond the built-in names, any Golly/LifeWiki ``.rle`` file loads directly
(``--pattern path/to/thing.rle``): ``#`` comment lines, the ``x = …, y = …,
rule = …`` header, and multi-state bodies (``.``/``A``–``X`` for states
0–24, as Generations/WireWorld patterns are published) are all understood,
and ``encode_rle`` writes the same format back out (``run --dump-rle``).
"""

from __future__ import annotations

import os
import re
from typing import Dict, Optional, Tuple

import numpy as np

# Run-length-encoded patterns in the standard Golly/LifeWiki RLE format.
# `b` = dead, `o` = alive, `$` = end of row, `!` = end of pattern.
RLE_PATTERNS: Dict[str, str] = {
    "blinker": "3o!",
    "block": "2o$2o!",
    "beehive": "b2o$o2bo$b2o!",
    "toad": "b3o$3o!",
    "beacon": "2o$2o$2b2o$2b2o!",
    "glider": "bob$2bo$3o!",
    "lwss": "b4o$o3bo$4bo$o2bo!",
    "pulsar": (
        "2b3o3b3o2b$13b$o4bobo4bo$o4bobo4bo$o4bobo4bo$2b3o3b3o2b$13b"
        "$2b3o3b3o2b$o4bobo4bo$o4bobo4bo$o4bobo4bo$13b$2b3o3b3o2b!"
    ),
    "r-pentomino": "b2o$2o$bo!",
    "pentadecathlon": "2bo4bo$2ob4ob2o$2bo4bo!",  # period-15 oscillator
    "diehard": "6bob$2o6b$bo3b3o!",  # vanishes after exactly 130 generations
    "acorn": "bo5b$3bo3b$2o2b3o!",  # 5206-gen methuselah (pop 633 stable)
    # Eater-1 (fishhook), in the orientation that absorbs the Gosper
    # gun's glider stream when anchored down-stream of the gun — the
    # periodic gun+eater board is the serve-memo bench's headline shape.
    "eater": "2o2b$o3b$b3o$3bo!",
    "gosper-glider-gun": (
        "24bo$22bobo$12b2o6b2o12b2o$11bo3bo4b2o12b2o$2o8bo5bo3b2o$2o8bo3bob2o4b"
        "obo$10bo5bo7bo$11bo3bo$12b2o!"
    ),
    # HighLife replicator (B36/S23).
    "replicator": "2b3o$bo2bo$o3bo$o2bob$3o!",
}

# Body tokens: binary ``b``/``o`` plus the multi-state alphabet ``.`` (dead)
# and ``A``–``X`` (states 1–24).  Golly's two-letter ``pA``-style encodings
# for states >24 are detected and rejected loudly rather than misread.
_RLE_TOKEN = re.compile(r"(\d*)([bo$!.A-X]|[p-y][A-X])")


def decode_rle(rle: str) -> np.ndarray:
    """Decode an RLE body string into a (H, W) uint8 state array."""
    rows = []
    row = []
    body = rle.replace("\n", "").replace(" ", "")
    for count_s, tag in _RLE_TOKEN.findall(body):
        count = int(count_s) if count_s else 1
        if len(tag) == 2:
            raise ValueError(
                f"multi-plane RLE token {tag!r}: states above 24 are not "
                "supported (max rule family here is 24-state Generations)"
            )
        if tag in ("b", "."):
            row.extend([0] * count)
        elif tag == "o":
            row.extend([1] * count)
        elif "A" <= tag <= "X":
            row.extend([ord(tag) - ord("A") + 1] * count)
        elif tag == "$":
            rows.append(row)
            # A multi-count `$` encodes blank rows.
            rows.extend([[]] * (count - 1))
            row = []
        elif tag == "!":
            # Only flush a non-empty in-progress row: a trailing `$` before
            # `!` (a style some writers emit) already flushed it, and must
            # not add a phantom blank row past the declared extent.
            if row:
                rows.append(row)
                row = []
            break
    if row:
        # Tolerate a missing '!' terminator (truncated paste) rather than
        # silently dropping the final row.
        rows.append(row)
    width = max((len(r) for r in rows), default=0)
    grid = np.zeros((len(rows), width), dtype=np.uint8)
    for y, r in enumerate(rows):
        grid[y, : len(r)] = r
    return grid


# A Golly/LifeWiki RLE header: "x = W, y = H" with an optional trailing
# ", rule = ...".  The rule is the header's final field and the rulestring
# itself may contain commas (LtL: "R5,B34-45,S33-57", Golly "R5,C0,M1,..."),
# so it captures to end of line.
_RLE_HEADER = re.compile(
    r"^\s*x\s*=\s*(\d+)\s*,\s*y\s*=\s*(\d+)\s*(?:,\s*rule\s*=\s*(.+?))?\s*$",
    re.IGNORECASE,
)


def parse_rle(text: str) -> Tuple[np.ndarray, Optional[str]]:
    """Parse a full RLE *file* (comments + header + body).

    Returns ``(grid, rule)`` where ``rule`` is the header's declared
    rulestring (or None when absent).  The grid is padded out to the
    header's declared ``x``/``y`` extent — RLE omits trailing dead cells
    and rows, but the declared bounding box is part of the pattern.
    """
    rule: Optional[str] = None
    size: Optional[Tuple[int, int]] = None
    body_lines = []
    for line in text.splitlines():
        s = line.strip()
        if not s or s.startswith("#"):
            continue
        if size is None and not body_lines:
            m = _RLE_HEADER.match(s)
            if m:
                size = (int(m.group(2)), int(m.group(1)))  # (H, W)
                rule = m.group(3)
                continue
        body_lines.append(s)
    grid = decode_rle("".join(body_lines))
    if size is not None:
        h, w = size
        gh, gw = grid.shape
        if gh > h or gw > w:
            raise ValueError(
                f"RLE body extent {gh}x{gw} exceeds declared header "
                f"x = {w}, y = {h}"
            )
        if (gh, gw) != (h, w):
            padded = np.zeros((h, w), dtype=np.uint8)
            padded[:gh, :gw] = grid
            grid = padded
    return grid, rule


def encode_rle(
    grid: np.ndarray, rule: Optional[str] = None, line_width: int = 70
) -> str:
    """Encode a (H, W) state array as a full RLE file string.

    Binary grids use ``b``/``o``; grids with states >1 use the multi-state
    ``.``/``A``–``X`` alphabet.  Round-trips through :func:`parse_rle`.
    """
    grid = np.asarray(grid)
    if grid.ndim != 2:
        raise ValueError(f"expected a 2-D grid, got shape {grid.shape}")
    h, w = grid.shape
    peak = int(grid.max(initial=0))
    if peak > 24:
        raise ValueError(f"state {peak} exceeds RLE's 24-state alphabet")
    multi = peak > 1

    def sym(v: int) -> str:
        if v == 0:
            return "." if multi else "b"
        if multi:
            return chr(ord("A") + v - 1)
        return "o"

    row_toks = []
    for y in range(h):
        row = grid[y]
        nz = np.nonzero(row)[0]
        if nz.size == 0:
            row_toks.append("")
            continue
        # Vectorized run segmentation (cost scales with the number of runs,
        # not cells): pattern-class boards encode fast at any size.  A dense
        # *random* board at headline sizes is not a target use — its RLE is
        # gigabytes of one-cell runs no matter how this is built.
        seg = row[: int(nz[-1]) + 1]
        bounds = np.flatnonzero(seg[1:] != seg[:-1]) + 1
        starts = np.concatenate(([0], bounds))
        ends = np.concatenate((bounds, [seg.size]))
        toks = []
        for n, v in zip((ends - starts).tolist(), seg[starts].tolist()):
            toks.append((str(n) if n > 1 else "") + sym(v))
        row_toks.append(toks)
    while row_toks and not row_toks[-1]:
        row_toks.pop()
    # Rows separate with `$`; blank rows collapse into the separator count
    # (dollars = separators owed before the next non-blank row lands).
    # toks stays a flat stream of small run tokens so line wrapping can
    # break inside long rows (the spec's 70-char line limit is per line,
    # not per row — a dense 65536-wide row far exceeds it).
    toks = []
    dollars = 0
    for r in row_toks:
        if r:
            if dollars:
                toks.append(f"{dollars}$" if dollars > 1 else "$")
            toks.extend(r)
            dollars = 1
        else:
            dollars += 1
    toks.append("!")
    lines = []
    cur = ""
    for t in toks:
        if cur and len(cur) + len(t) > line_width:
            lines.append(cur)
            cur = ""
        cur += t
    if cur:
        lines.append(cur)
    header = f"x = {w}, y = {h}"
    if rule:
        header += f", rule = {rule}"
    return header + "\n" + "\n".join(lines) + "\n"


def load_rle_file(path: str) -> Tuple[np.ndarray, Optional[str]]:
    """Load a ``.rle`` pattern file → ``(grid, declared_rule_or_None)``."""
    with open(path, "r", encoding="utf-8") as f:
        return parse_rle(f.read())


def _looks_like_file(name: str) -> bool:
    return name.lower().endswith(".rle") or os.sep in name


def resolve_pattern(name: str) -> Tuple[np.ndarray, Optional[str]]:
    """Resolve a pattern name or ``.rle`` path → ``(grid, declared_rule)``.

    Only ``.rle`` files declare a rule (the header's ``rule =`` field);
    built-in named patterns return None there.  One call, one file read —
    this is the primitive behind :func:`get_pattern`, and what callers that
    also want the declared rule (e.g. the run-vs-pattern rule-mismatch
    warning) should use.
    """
    if _looks_like_file(name):
        if not os.path.exists(name):
            raise KeyError(f"pattern file not found: {name!r}")
        return load_rle_file(name)
    return get_pattern(name), None




# Multi-state patterns (state digits), for families RLE's b/o can't encode.
# Wireworld states: 0 empty, 1 electron head, 2 tail, 3 conductor.
DIGIT_PATTERNS: Dict[str, Tuple[str, ...]] = {
    # A 10-cell octagonal wire loop (corners cut so every path cell has
    # exactly 2 path neighbors — square corners would double the electron
    # through Moore diagonals) with one electron circulating: period 10.
    "wireworld-clock": (
        "02330",
        "10003",
        "30003",
        "03330",
    ),
}


def get_pattern(name: str) -> np.ndarray:
    """Look up a pattern as a (H, W) uint8 array.

    ``name`` is either a built-in canonical name or a path to a Golly/
    LifeWiki ``.rle`` file (anything ending in ``.rle`` or containing a
    path separator).
    """
    if _looks_like_file(name):
        return resolve_pattern(name)[0]
    key = name.strip().lower()
    if key in DIGIT_PATTERNS:
        return np.array(
            [[int(ch) for ch in row] for row in DIGIT_PATTERNS[key]],
            dtype=np.uint8,
        )
    if key not in RLE_PATTERNS:
        raise KeyError(
            f"unknown pattern {name!r}; have "
            f"{sorted(RLE_PATTERNS) + sorted(DIGIT_PATTERNS)}"
        )
    return decode_rle(RLE_PATTERNS[key])


def place(
    board: np.ndarray, pattern: np.ndarray, top_left: Tuple[int, int] = (0, 0)
) -> np.ndarray:
    """Return a copy of ``board`` with ``pattern`` stamped at ``top_left``
    (toroidal wrap if the pattern crosses the board edge)."""
    out = np.array(board, copy=True)
    h, w = out.shape
    py, px = pattern.shape
    if py > h or px > w:
        raise ValueError(
            f"pattern {pattern.shape} does not fit board {board.shape}"
        )
    y0, x0 = top_left
    ys = (np.arange(py) + y0) % h
    xs = (np.arange(px) + x0) % w
    out[np.ix_(ys, xs)] = pattern
    return out


def pattern_board(
    name: str, board_shape: Tuple[int, int], top_left: Tuple[int, int] = (0, 0)
) -> np.ndarray:
    """An empty (H, W) uint8 torus with a named pattern stamped on it."""
    board = np.zeros(board_shape, dtype=np.uint8)
    return place(board, get_pattern(name), top_left)


def random_grid(
    shape: Tuple[int, int],
    *,
    density: float = 0.5,
    seed: int = 0,
    states: int = 2,
) -> np.ndarray:
    """Random initial board — the reference's Bernoulli(1/2) initial state
    (``BoardCreator.scala:23``), generalized to a density knob and, for
    Generations rules, to live-state-only randomness (refractory states are
    never part of a fresh board)."""
    del states  # live/dead only; refractory states arise from dynamics
    rng = np.random.default_rng(seed)
    # Chunked uint16 thresholding: rng.random would allocate 8 bytes/cell
    # (34 GiB at 65536²); this path peaks at the uint8 board plus one
    # ~256 MiB scratch block, with density quantized to 1/65536.
    h, w = shape
    thresh = max(0, min(65536, round(density * 65536)))
    # Saturated densities never reach the comparison: 65536 overflows uint16
    # (np.less with an out-of-range python int segfaults NumPy 2.0.2).
    if thresh == 0:
        return np.zeros(shape, dtype=np.uint8)
    if thresh == 65536:
        return np.ones(shape, dtype=np.uint8)
    out = np.empty(shape, dtype=np.uint8)
    t16 = np.uint16(thresh)
    rows_per = max(1, (1 << 27) // max(1, w))
    for y in range(0, h, rows_per):
        block = rng.integers(
            0, 65536, size=(min(rows_per, h - y), w), dtype=np.uint16
        )
        np.less(block, t16, out=out[y : y + block.shape[0]])
    return out
