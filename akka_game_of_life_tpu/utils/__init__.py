from akka_game_of_life_tpu.utils.patterns import (  # noqa: F401
    decode_rle,
    get_pattern,
    place,
    random_grid,
)
