"""Native (C++) runtime components, loaded via ctypes.

The reference's runtime is JVM-native (Akka on Netty); this package plays the
same role for the host-side half of the framework: the per-cell actor engine
(the CPU parity backend, BASELINE config 1) compiled to machine code.  The
TPU compute path stays JAX/XLA/Pallas — native code here is for the parts
that run on the host CPU.

Components: the per-cell actor engine (``actor_engine.cpp`` — the CPU parity
backend, BASELINE config 1) and the SWAR chunk stepper (``swar_kernel.cpp``
— 64 cells/uint64 lane, the host twin of the TPU bit-packed kernel).

Build model: no pip, no pybind11 — the translation units in ``_SRCS`` are
compiled together on demand with ``g++ -O2 -shared -fPIC`` into one
content-addressed ``.so`` (digest spans every source, so editing either
file rebuilds), loaded with ctypes.  ``load()`` returns None (and the
callers fall back to the pure-Python engines) when no compiler is
available.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_SRCS = [
    os.path.join(os.path.dirname(__file__), "actor_engine.cpp"),
    os.path.join(os.path.dirname(__file__), "swar_kernel.cpp"),
]
_BUILD_DIR = os.path.join(os.path.dirname(__file__), "_build")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed: Optional[str] = None


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ae_create.restype = ctypes.c_void_p
    lib.ae_create.argtypes = [
        ctypes.c_int32, ctypes.c_int32, u8p,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.ae_destroy.argtypes = [ctypes.c_void_p]
    lib.ae_advance_to.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.ae_crash_cell.argtypes = [ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.ae_feed_halo.argtypes = [ctypes.c_void_p, ctypes.c_int32, u8p]
    lib.ae_get_board.argtypes = [ctypes.c_void_p, u8p]
    lib.ae_min_epoch.restype = ctypes.c_int32
    lib.ae_min_epoch.argtypes = [ctypes.c_void_p]
    lib.ae_messages.restype = ctypes.c_int64
    lib.ae_messages.argtypes = [ctypes.c_void_p]
    lib.ae_prune_below.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.swar_chunk.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, u8p,
    ]
    lib.swar_wire_chunk.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_uint32, u8p,
    ]
    lib.swar_gen_chunk.argtypes = [
        u8p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32,
        ctypes.c_uint32, ctypes.c_uint32, ctypes.c_int32, u8p,
    ]
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Compile (once per source revision) and load the native engine.

    Returns None when unavailable (no g++ / build error); the reason is kept
    in :func:`load_error` so callers can surface it.
    """
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed is not None:
            return None
        try:
            flags = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]
            hasher = hashlib.sha256()
            # The digest spans sources AND compiler argv: a flag-only change
            # (e.g. adding -pthread) must invalidate the cached .so, or a
            # stale binary built under the old flags loads silently.
            hasher.update(" ".join(flags).encode())
            for src in _SRCS:
                with open(src, "rb") as f:
                    hasher.update(f.read())
            digest = hasher.hexdigest()[:16]
            so_path = os.path.join(_BUILD_DIR, f"gol_native_{digest}.so")
            if not os.path.exists(so_path):
                os.makedirs(_BUILD_DIR, exist_ok=True)
                tmp = so_path + f".tmp{os.getpid()}"
                subprocess.run(
                    [*flags, *_SRCS, "-o", tmp],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
                os.replace(tmp, so_path)  # atomic: concurrent builders race safely
            _lib = _configure(ctypes.CDLL(so_path))
            return _lib
        except (OSError, subprocess.SubprocessError) as e:
            stderr = getattr(e, "stderr", b"") or b""
            _load_failed = f"{type(e).__name__}: {e} {stderr.decode(errors='replace')[:500]}"
            return None


def load_error() -> Optional[str]:
    return _load_failed


def available() -> bool:
    return load() is not None
