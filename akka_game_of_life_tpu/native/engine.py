"""ctypes wrappers presenting the native actor engine with the same API as
:mod:`akka_game_of_life_tpu.runtime.actor_engine` (ActorBoard /
ActorTileEngine), so the two are drop-in interchangeable wherever the
per-cell-actor backend is selected."""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from akka_game_of_life_tpu.native import load
from akka_game_of_life_tpu.ops.rules import resolve_rule

Position = Tuple[int, int]


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class NativeActorBoard:
    """Toroidal per-cell actor board backed by the C++ event loop."""

    def __init__(self, board: np.ndarray, rule) -> None:
        lib = load()
        if lib is None:
            from akka_game_of_life_tpu.native import load_error

            raise RuntimeError(f"native engine unavailable: {load_error()}")
        self._lib = lib
        self.rule = resolve_rule(rule)
        if self.rule.radius != 1:
            raise ValueError(
                "the native per-cell engine is Moore-8 (radius 1); "
                "radius-R ltl rules run on the dense kernel"
            )
        board = np.ascontiguousarray(board, dtype=np.uint8)
        self.shape = board.shape
        h, w = board.shape
        self._ptr = lib.ae_create(
            h, w, _as_u8p(board),
            self.rule.birth_mask, self.rule.survive_mask, self.rule.states, 0,
            0 if self.rule.is_totalistic else 1,
        )
        if not self._ptr:
            raise ValueError(f"board {h}x{w} too large for the per-cell engine")
        self.global_epoch = 0

    def __del__(self) -> None:
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.ae_destroy(ptr)
            self._ptr = None

    # -- coordinator API (ActorBoard parity) ---------------------------------

    def advance_to(self, target_epoch: int) -> None:
        self.global_epoch = max(self.global_epoch, target_epoch)
        self._lib.ae_advance_to(self._ptr, target_epoch)

    def crash_cell(self, pos: Position) -> None:
        self._lib.ae_crash_cell(self._ptr, pos[0], pos[1])

    def board_at_current(self) -> np.ndarray:
        out = np.empty(self.shape, dtype=np.uint8)
        self._lib.ae_get_board(self._ptr, _as_u8p(out))
        return out

    def min_epoch(self) -> int:
        return int(self._lib.ae_min_epoch(self._ptr))

    def prune_histories_below(self, epoch: int) -> None:
        self._lib.ae_prune_below(self._ptr, epoch)

    @property
    def messages_processed(self) -> int:
        return int(self._lib.ae_messages(self._ptr))


class NativeActorTileEngine:
    """``engine="actor-native"`` adapter for BackendWorker: the ghost-ring
    tile variant (remote neighbors fed from the cluster halo)."""

    def __init__(self, rule) -> None:
        self.rule = resolve_rule(rule)
        if self.rule.radius != 1:
            raise ValueError(
                "the native per-cell engine is Moore-8 (radius 1); "
                "radius-R ltl rules run on the dense kernel"
            )
        self._lib = load()
        if self._lib is None:
            from akka_game_of_life_tpu.native import load_error

            raise RuntimeError(f"native engine unavailable: {load_error()}")
        self._ptr: Optional[int] = None
        self._shape: Optional[Tuple[int, int]] = None
        self._epoch = 0

    def __del__(self) -> None:
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.ae_destroy(ptr)
            self._ptr = None

    def step(self, padded: np.ndarray) -> np.ndarray:
        padded = np.ascontiguousarray(padded, dtype=np.uint8)
        interior = padded[1:-1, 1:-1]
        if self._ptr is None:
            h, w = interior.shape
            self._shape = (h, w)
            arr = np.ascontiguousarray(interior)
            self._ptr = self._lib.ae_create(
                h, w, _as_u8p(arr),
                self.rule.birth_mask, self.rule.survive_mask,
                self.rule.states, 1,
                0 if self.rule.is_totalistic else 1,
            )
            if not self._ptr:
                raise ValueError(
                    f"tile {h}x{w} too large for the per-cell engine"
                )
        self._lib.ae_feed_halo(self._ptr, self._epoch, _as_u8p(padded))
        self._epoch += 1
        self._lib.ae_advance_to(self._ptr, self._epoch)
        assert int(self._lib.ae_min_epoch(self._ptr)) == self._epoch
        self._lib.ae_prune_below(self._ptr, self._epoch - 1)
        out = np.empty(self._shape, dtype=np.uint8)
        self._lib.ae_get_board(self._ptr, _as_u8p(out))
        return out



def _native_chunk(padded, steps, halo, call):
    """Shared body of the chunk wrappers: steps/halo contract, library
    load, contiguity, and interior-output allocation; ``call(lib, padded,
    ph, pw, out)`` invokes the kernel."""
    if steps > halo:
        raise ValueError(f"steps={steps} > halo={halo}")
    lib = load()
    if lib is None:
        from akka_game_of_life_tpu.native import load_error

        raise RuntimeError(f"native engine unavailable: {load_error()}")
    padded = np.ascontiguousarray(padded, dtype=np.uint8)
    ph, pw = padded.shape
    out = np.empty((ph - 2 * halo, pw - 2 * halo), dtype=np.uint8)
    call(lib, padded, ph, pw, out)
    return out


def swar_chunk_native(
    padded: np.ndarray, steps: int, halo: int, rule
) -> np.ndarray:
    """Advance the (h, w) interior of a width-``halo`` padded slab by
    ``steps`` (<= halo) generations with the C++ SWAR kernel (64 cells per
    uint64 lane; native/swar_kernel.cpp) — the host-CPU twin of the TPU
    bit-packed kernel, and the machine-code replacement for the numpy
    engine's roll-sum stepping on binary rules."""
    rule = resolve_rule(rule)
    if not (rule.is_binary and rule.is_totalistic):
        raise ValueError(
            "native SWAR kernel supports binary totalistic rules only"
        )
    return _native_chunk(
        padded, steps, halo,
        lambda lib, p, ph, pw, out: lib.swar_chunk(
            _as_u8p(p), ph, pw, steps, halo,
            rule.birth_mask, rule.survive_mask, _as_u8p(out),
        ),
    )


def swar_wire_chunk_native(
    padded: np.ndarray, steps: int, halo: int, rule
) -> np.ndarray:
    """WireWorld twin of :func:`swar_chunk_native`: the 4-state CA as two
    uint64 bit planes through the same carry-save head-count adders
    (native/swar_kernel.cpp ``swar_wire_chunk``)."""
    rule = resolve_rule(rule)
    if rule.kind != "wireworld":
        raise ValueError(f"expected a wireworld rule, got {rule}")
    return _native_chunk(
        padded, steps, halo,
        lambda lib, p, ph, pw, out: lib.swar_wire_chunk(
            _as_u8p(p), ph, pw, steps, halo, rule.birth_mask, _as_u8p(out)
        ),
    )


def swar_gen_chunk_native(
    padded: np.ndarray, steps: int, halo: int, rule
) -> np.ndarray:
    """Generations twin of :func:`swar_chunk_native`: m bit planes with
    ripple-carry refractory decay (native/swar_kernel.cpp
    ``swar_gen_chunk``)."""
    rule = resolve_rule(rule)
    # Rule() caps states at 255, so totalistic + multi-state is the whole
    # gate.
    if not (rule.is_totalistic and not rule.is_binary):
        raise ValueError(
            f"expected a multi-state Generations rule, got {rule}"
        )
    return _native_chunk(
        padded, steps, halo,
        lambda lib, p, ph, pw, out: lib.swar_gen_chunk(
            _as_u8p(p), ph, pw, steps, halo,
            rule.birth_mask, rule.survive_mask, rule.states, _as_u8p(out),
        ),
    )
