// Native SWAR stepper for binary life-like rules — the host-CPU twin of the
// TPU bit-packed kernel (ops/bitpack.py): 64 cells per uint64 lane,
// carry-save-adder Moore counts over shared per-row triple sums, B/S rule as
// count-equality predicate planes.
//
// Reference capability note: this is the same collapse of the per-cell actor
// protocol (/root/reference/src/main/scala/gameoflife/CellActor.scala:63-89,
// NextStateCellGathererActor.scala:32-45) into pure arithmetic that the XLA
// kernels perform, compiled for the host so the cluster's CPU engine matches
// the reference's JVM-native runtime with machine code instead of actor
// message storms.
//
// Contract (mirrors runtime/backend._np_chunk): `swar_chunk` takes a
// width-`halo` padded slab (ph, pw) = (h + 2*halo, w + 2*halo) of 0/1 uint8
// cells and advances the (h, w) interior by `steps` <= halo generations,
// treating everything beyond the slab as dead.  Each step's garbage front
// moves one cell inward from the slab edge, so after `steps` steps the
// interior slice is exact — the same peeling argument as step_padded_np.
//
// Build: compiled into the shared native .so by native/__init__.py (g++ -O2,
// no external deps).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct Planes {
  // Per-row bit planes with one guard word on each side (kept zero) so the
  // west/east cross-word shifts need no edge branches.
  int rows, words;
  std::vector<uint64_t> data;  // rows * (words + 2)

  Planes(int r, int w) : rows(r), words(w), data((size_t)r * (w + 2), 0) {}
  uint64_t* row(int r) { return data.data() + (size_t)r * (words + 2) + 1; }
  void clear() { std::fill(data.begin(), data.end(), 0); }
};

// Horizontal 3-cell full-adder planes for one row: s + 2c = west+center+east
// (center included — survive thresholds shift by +1, as in ops/bitpack.py).
inline void row_triple(const uint64_t* x, uint64_t* s, uint64_t* c, int words) {
  for (int i = 0; i < words; ++i) {
    uint64_t w = (x[i] << 1) | (x[i - 1] >> 63);
    uint64_t e = (x[i] >> 1) | (x[i + 1] << 63);
    uint64_t xw = x[i] ^ w;
    s[i] = xw ^ e;
    c[i] = (x[i] & w) | (e & xw);
  }
}

// Assemble count = (sN+sC+sS) + 2*(cN+cC+cS) — the 9-cell Moore sum as
// bit planes (b3, b2, b1, b0) — shared by both chunk kernels' combine
// loops (the C++ twin of ops/bitpack.py _count_bits).
inline void nine_sum(uint64_t sN, uint64_t sC, uint64_t sS, uint64_t cN,
                     uint64_t cC, uint64_t cS, uint64_t& b3, uint64_t& b2,
                     uint64_t& b1, uint64_t& b0) {
  uint64_t sNC = sN ^ sC;
  b0 = sNC ^ sS;
  uint64_t p1 = (sN & sC) | (sS & sNC);
  uint64_t cNC = cN ^ cC;
  uint64_t q0 = cNC ^ cS;
  uint64_t q1 = (cN & cC) | (cS & cNC);
  b1 = p1 ^ q0;
  uint64_t r2 = p1 & q0;
  b2 = q1 ^ r2;
  b3 = q1 & r2;
}

// Row-band parallelism: both per-step phases (triple sums; combine) are
// row-local over read-only inputs, so bands need no locks — only the join
// between phases (phase B reads neighbor rows' phase-A output).  Threads
// are (re)spawned per phase; at the slab sizes where threading is enabled
// the spawn cost is noise next to the band compute.

// Concurrent swar_chunk callers in this process (the in-process cluster
// harness runs several workers as threads): each sizes its pool against
// its share of the cores so N tiles don't spawn N * cores threads.
std::atomic<int> g_active_chunks{0};

inline int thread_count(int rows, int words) {
  if ((int64_t)rows * words < (1 << 14)) return 1;  // small slabs: spawn cost wins
  int t = (int)std::thread::hardware_concurrency();
  if (const char* env = std::getenv("GOL_SWAR_THREADS")) {
    int v = std::atoi(env);
    if (v > 0) t = v;
  }
  int sharers = std::max(1, g_active_chunks.load(std::memory_order_relaxed));
  return std::max(1, std::min({t / sharers, 16, rows / 8}));
}

template <typename Fn>
inline void parallel_rows(int rows, int threads, const Fn& fn) {
  if (threads <= 1) {
    fn(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(threads - 1);
  int band = (rows + threads - 1) / threads;
  try {
    // Bands 1..n on spawned threads; band 0 runs on the calling thread
    // below, so no core idles in join.
    for (int t = 1; t < threads; ++t) {
      int r0 = t * band, r1 = std::min(rows, r0 + band);
      if (r0 >= r1) break;
      pool.emplace_back([&, r0, r1] { fn(r0, r1); });
    }
  } catch (...) {
    // Thread creation failed (e.g. cgroup task limits): join whatever
    // started, then recompute everything serially — both phases write
    // deterministic values from read-only inputs, so overlapping
    // recomputation is idempotent and an exception never escapes the
    // extern "C" boundary.
    for (auto& th : pool) th.join();
    fn(0, rows);
    return;
  }
  fn(0, std::min(rows, band));
  for (auto& th : pool) th.join();
}


// Which counts the rule actually tests, mirroring ops/bitpack.py: birth
// tests count n directly; survive tests count n+1 (the live center is
// inside the 9-sum); a count in BOTH sets needs no center masking.
struct Need {
  int n;
  enum { ALWAYS, BIRTH, SURVIVE } kind;
};

struct NeedSet {
  std::vector<Need> needs;
  bool any_birth = false, any_survive = false;
};

inline NeedSet build_needs(uint32_t birth_mask, uint32_t survive_mask) {
  NeedSet ns;
  for (int n = 0; n <= 9; ++n) {
    bool b = (birth_mask >> n) & 1;
    bool s = n > 0 && ((survive_mask >> (n - 1)) & 1);
    if (b && s)
      ns.needs.push_back({n, Need::ALWAYS});
    else if (b) {
      ns.needs.push_back({n, Need::BIRTH});
      ns.any_birth = true;
    } else if (s) {
      ns.needs.push_back({n, Need::SURVIVE});
      ns.any_survive = true;
    }
  }
  return ns;
}

// RAII counter of concurrent chunk callers (thread_count divides the core
// budget by it).
struct ActiveGuard {
  ActiveGuard() { g_active_chunks.fetch_add(1, std::memory_order_relaxed); }
  ~ActiveGuard() { g_active_chunks.fetch_sub(1, std::memory_order_relaxed); }
};

}  // namespace

extern "C" void swar_chunk(const uint8_t* padded, int32_t ph, int32_t pw,
                           int32_t steps, int32_t halo,
                           uint32_t birth_mask, uint32_t survive_mask,
                           uint8_t* out) {
  const int words = (pw + 63) / 64;
  Planes cur(ph, words), next(ph, words);
  Planes S(ph, words), C(ph, words);

  // Pack the uint8 slab into LSB-first bitboards (bit i of word k = column
  // 64k + i), zeros beyond pw.
  for (int r = 0; r < ph; ++r) {
    const uint8_t* src = padded + (size_t)r * pw;
    uint64_t* dst = cur.row(r);
    for (int x = 0; x < pw; ++x)
      if (src[x]) dst[x >> 6] |= (uint64_t)1 << (x & 63);
  }

  // Predicate planes are only needed for counts the rule actually tests
  // (mirroring ops/bitpack.py, which builds eq(n) per masked count): for
  // Conway that is {3, 4} instead of all ten — roughly halving the hottest
  // loop's ALU work.  Precomputed once; the inner loop never consults the
  // runtime masks.
  // A count in BOTH sets makes the cell alive regardless of its current
  // state (count n = n neighbors when dead, n-1 when alive), so those
  // predicate planes skip the x masking entirely — for Conway the combine
  // collapses to eq3 | (x & eq4), mirroring ops/bitpack.py _combine_rows.
  const NeedSet ns = build_needs(birth_mask, survive_mask);
  const std::vector<Need>& needs = ns.needs;
  const bool any_birth = ns.any_birth, any_survive = ns.any_survive;

  std::vector<uint64_t> zero(words + 2, 0);
  ActiveGuard guard;
  const int threads = thread_count(ph, words);
  for (int step = 0; step < steps; ++step) {
    parallel_rows(ph, threads, [&](int r0, int r1) {
      for (int r = r0; r < r1; ++r)
        row_triple(cur.row(r), S.row(r), C.row(r), words);
    });
    parallel_rows(ph, threads, [&](int band0, int band1) {
    for (int r = band0; r < band1; ++r) {
      const uint64_t* sN = r > 0 ? S.row(r - 1) : zero.data() + 1;
      const uint64_t* cN = r > 0 ? C.row(r - 1) : zero.data() + 1;
      const uint64_t* sS = r < ph - 1 ? S.row(r + 1) : zero.data() + 1;
      const uint64_t* cS = r < ph - 1 ? C.row(r + 1) : zero.data() + 1;
      const uint64_t* sC = S.row(r);
      const uint64_t* cC = C.row(r);
      const uint64_t* x = cur.row(r);
      uint64_t* o = next.row(r);
      for (int i = 0; i < words; ++i) {
        uint64_t b3, b2, b1, b0;
        nine_sum(sN[i], sC[i], sS[i], cN[i], cC[i], cS[i], b3, b2, b1, b0);
        uint64_t always = 0, birth = 0, survive = 0;
        for (const Need& nd : needs) {
          // Predicate plane: count == nd.n.
          uint64_t t = (nd.n & 8 ? b3 : ~b3) & (nd.n & 4 ? b2 : ~b2) &
                       (nd.n & 2 ? b1 : ~b1) & (nd.n & 1 ? b0 : ~b0);
          if (nd.kind == Need::ALWAYS)
            always |= t;
          else if (nd.kind == Need::BIRTH)
            birth |= t;
          else
            survive |= t;
        }
        uint64_t v = always;
        // Loop-invariant branches: hoisted by the compiler, so rules with
        // no birth-only / survive-only counts pay nothing for the masks.
        if (any_birth) v |= ~x[i] & birth;
        if (any_survive) v |= x[i] & survive;
        o[i] = v;
      }
      // Keep the out-of-slab columns dead (shift guards must stay zero and
      // bits >= pw must not become fake neighbors through later steps).
      if (pw & 63) o[words - 1] &= ((uint64_t)1 << (pw & 63)) - 1;
    }
    });
    std::swap(cur.data, next.data);
  }

  // Extract the exact (h, w) interior.
  const int h = ph - 2 * halo, w = pw - 2 * halo;
  for (int r = 0; r < h; ++r) {
    const uint64_t* src = cur.row(r + halo);
    uint8_t* dst = out + (size_t)r * w;
    for (int x = 0; x < w; ++x) {
      int col = x + halo;
      dst[x] = (src[col >> 6] >> (col & 63)) & 1;
    }
  }
}

// WireWorld chunk: the 4-state digital-logic CA as TWO bit planes with the
// state's binary encoding (empty=00, head=01, tail=10, conductor=11), the
// same layout as the TPU plane kernel (ops/bitpack_gen.py).  Heads
// (p0 & ~p1) feed the shared carry-save adders; the transition collapses to
//
//   next_p0 = p1                                  // tail|conductor gain p0
//   next_p1 = (p0 ^ p1) | (p0 & p1 & ~excite)     // head|tail | calm conductor
//
// where `excite` is the head-count-in-birth predicate with NO +1 shift (a
// conductor center is never a head, so it cannot self-count).  Everything
// beyond the slab is empty (00) — the same peeling contract as swar_chunk.
extern "C" void swar_wire_chunk(const uint8_t* padded, int32_t ph, int32_t pw,
                                int32_t steps, int32_t halo,
                                uint32_t birth_mask, uint8_t* out) {
  const int words = (pw + 63) / 64;
  Planes p0(ph, words), p1(ph, words), n0(ph, words), n1(ph, words);
  Planes H(ph, words), S(ph, words), C(ph, words);

  for (int r = 0; r < ph; ++r) {
    const uint8_t* src = padded + (size_t)r * pw;
    uint64_t* d0 = p0.row(r);
    uint64_t* d1 = p1.row(r);
    for (int x = 0; x < pw; ++x) {
      uint8_t v = src[x];
      if (v & 1) d0[x >> 6] |= (uint64_t)1 << (x & 63);
      if (v & 2) d1[x >> 6] |= (uint64_t)1 << (x & 63);
    }
  }

  // Counts the birth mask actually tests ({1, 2} for standard wireworld).
  std::vector<int> excite_counts;
  for (int n = 0; n <= 9; ++n)
    if ((birth_mask >> n) & 1) excite_counts.push_back(n);

  std::vector<uint64_t> zero(words + 2, 0);
  ActiveGuard guard;
  const int threads = thread_count(ph, words);
  for (int step = 0; step < steps; ++step) {
    parallel_rows(ph, threads, [&](int r0, int r1) {
      for (int r = r0; r < r1; ++r) {
        const uint64_t* a = p0.row(r);
        const uint64_t* b = p1.row(r);
        uint64_t* hrow = H.row(r);
        for (int i = 0; i < words; ++i) hrow[i] = a[i] & ~b[i];  // heads
        row_triple(hrow, S.row(r), C.row(r), words);
      }
    });
    parallel_rows(ph, threads, [&](int band0, int band1) {
      for (int r = band0; r < band1; ++r) {
        const uint64_t* sN = r > 0 ? S.row(r - 1) : zero.data() + 1;
        const uint64_t* cN = r > 0 ? C.row(r - 1) : zero.data() + 1;
        const uint64_t* sS = r < ph - 1 ? S.row(r + 1) : zero.data() + 1;
        const uint64_t* cS = r < ph - 1 ? C.row(r + 1) : zero.data() + 1;
        const uint64_t* sC = S.row(r);
        const uint64_t* cC = C.row(r);
        const uint64_t* a = p0.row(r);
        const uint64_t* b = p1.row(r);
        uint64_t* o0 = n0.row(r);
        uint64_t* o1 = n1.row(r);
        for (int i = 0; i < words; ++i) {
          uint64_t b3, b2, b1, b0;
          nine_sum(sN[i], sC[i], sS[i], cN[i], cC[i], cS[i], b3, b2, b1, b0);
          uint64_t excite = 0;
          for (int n : excite_counts)
            excite |= (n & 8 ? b3 : ~b3) & (n & 4 ? b2 : ~b2) &
                      (n & 2 ? b1 : ~b1) & (n & 1 ? b0 : ~b0);
          o0[i] = b[i];
          o1[i] = (a[i] ^ b[i]) | (a[i] & b[i] & ~excite);
        }
        // Out-of-slab columns stay empty (00) through later steps.
        if (pw & 63) {
          uint64_t m = ((uint64_t)1 << (pw & 63)) - 1;
          o0[words - 1] &= m;
          o1[words - 1] &= m;
        }
      }
    });
    std::swap(p0.data, n0.data);
    std::swap(p1.data, n1.data);
  }

  const int h = ph - 2 * halo, w = pw - 2 * halo;
  for (int r = 0; r < h; ++r) {
    const uint64_t* s0 = p0.row(r + halo);
    const uint64_t* s1 = p1.row(r + halo);
    uint8_t* dst = out + (size_t)r * w;
    for (int x = 0; x < w; ++x) {
      int col = x + halo;
      dst[x] = (uint8_t)(((s0[col >> 6] >> (col & 63)) & 1) |
                         (((s1[col >> 6] >> (col & 63)) & 1) << 1));
    }
  }
}

// Generations chunk: m = ceil(log2(states)) bit planes, decay semantics as
// in ops/bitpack_gen.py — dead -> 1 on birth-hit; alive -> 1 on
// survive-hit else state+1; refractory -> state+1 wrapping S-1 -> 0.  The
// counted plane is state==1; survive thresholds shift by +1 (the live
// center is inside the 9-sum).  Beyond-slab cells stay dead (00..0), the
// same peeling contract as the other chunks.
extern "C" void swar_gen_chunk(const uint8_t* padded, int32_t ph, int32_t pw,
                               int32_t steps, int32_t halo,
                               uint32_t birth_mask, uint32_t survive_mask,
                               int32_t states, uint8_t* out) {
  const int words = (pw + 63) / 64;
  int m = 1;
  while ((1 << m) < states) ++m;
  std::vector<Planes> cur, nxt;
  for (int k = 0; k < m; ++k) {
    cur.emplace_back(ph, words);
    nxt.emplace_back(ph, words);
  }
  Planes A(ph, words), S(ph, words), C(ph, words);

  for (int r = 0; r < ph; ++r) {
    const uint8_t* src = padded + (size_t)r * pw;
    for (int k = 0; k < m; ++k) {
      uint64_t* dst = cur[k].row(r);
      for (int x = 0; x < pw; ++x)
        if ((src[x] >> k) & 1) dst[x >> 6] |= (uint64_t)1 << (x & 63);
    }
  }

  const NeedSet ns = build_needs(birth_mask, survive_mask);
  const std::vector<Need>& needs = ns.needs;
  const bool any_birth = ns.any_birth, any_survive = ns.any_survive;
  const uint32_t last = (uint32_t)states - 1;  // the wrapping state

  std::vector<uint64_t> zero(words + 2, 0);
  ActiveGuard guard;
  const int threads = thread_count(ph, words);
  for (int step = 0; step < steps; ++step) {
    parallel_rows(ph, threads, [&](int r0, int r1) {
      for (int r = r0; r < r1; ++r) {
        uint64_t* arow = A.row(r);
        // alive = state == 1 = p0 & ~p1 & ... & ~p_{m-1}
        const uint64_t* q0 = cur[0].row(r);
        for (int i = 0; i < words; ++i) arow[i] = q0[i];
        for (int k = 1; k < m; ++k) {
          const uint64_t* qk = cur[k].row(r);
          for (int i = 0; i < words; ++i) arow[i] &= ~qk[i];
        }
        row_triple(arow, S.row(r), C.row(r), words);
      }
    });
    parallel_rows(ph, threads, [&](int band0, int band1) {
      for (int r = band0; r < band1; ++r) {
        const uint64_t* sN = r > 0 ? S.row(r - 1) : zero.data() + 1;
        const uint64_t* cN = r > 0 ? C.row(r - 1) : zero.data() + 1;
        const uint64_t* sS = r < ph - 1 ? S.row(r + 1) : zero.data() + 1;
        const uint64_t* cS = r < ph - 1 ? C.row(r + 1) : zero.data() + 1;
        const uint64_t* sC = S.row(r);
        const uint64_t* cC = C.row(r);
        const uint64_t* alive = A.row(r);
        for (int i = 0; i < words; ++i) {
          uint64_t b3, b2, b1, b0;
          nine_sum(sN[i], sC[i], sS[i], cN[i], cC[i], cS[i], b3, b2, b1, b0);
          uint64_t always = 0, birth = 0, survive = 0;
          for (const Need& nd : needs) {
            uint64_t t = (nd.n & 8 ? b3 : ~b3) & (nd.n & 4 ? b2 : ~b2) &
                         (nd.n & 2 ? b1 : ~b1) & (nd.n & 1 ? b0 : ~b0);
            if (nd.kind == Need::ALWAYS)
              always |= t;
            else if (nd.kind == Need::BIRTH)
              birth |= t;
            else
              survive |= t;
          }
          uint64_t dead = ~(uint64_t)0, wrap = ~(uint64_t)0;
          uint64_t p[8], inc[8];
          for (int k = 0; k < m; ++k) {
            p[k] = cur[k].row(r)[i];
            dead &= ~p[k];
            wrap &= ((last >> k) & 1) ? p[k] : ~p[k];
          }
          // state+1 over the planes (ripple carry; the wrap mask zeroes
          // the only state that can overflow).
          uint64_t carry = 0;
          for (int k = 0; k < m; ++k) {
            inc[k] = k == 0 ? ~p[0] : p[k] ^ carry;
            carry = k == 0 ? p[0] : (p[k] & carry);
          }
          uint64_t to_one = always;
          if (any_birth) to_one |= dead & birth;
          if (any_survive) to_one |= alive[i] & survive;
          // ALWAYS counts still require a live-or-dead center (refractory
          // cells neither survive nor give birth).
          to_one &= dead | alive[i];
          uint64_t advance = ~dead & ~to_one & ~wrap;
          for (int k = 0; k < m; ++k)
            nxt[k].row(r)[i] = (k == 0 ? to_one : 0) | (advance & inc[k]);
        }
        // Out-of-slab columns stay dead through later steps.
        if (pw & 63) {
          uint64_t mask = ((uint64_t)1 << (pw & 63)) - 1;
          for (int k = 0; k < m; ++k) nxt[k].row(r)[words - 1] &= mask;
        }
      }
    });
    for (int k = 0; k < m; ++k) std::swap(cur[k].data, nxt[k].data);
  }

  const int h = ph - 2 * halo, w = pw - 2 * halo;
  for (int r = 0; r < h; ++r) {
    uint8_t* dst = out + (size_t)r * w;
    for (int x = 0; x < w; ++x) dst[x] = 0;
    for (int k = 0; k < m; ++k) {
      const uint64_t* src = cur[k].row(r + halo);
      for (int x = 0; x < w; ++x) {
        int col = x + halo;
        dst[x] |= (uint8_t)(((src[col >> 6] >> (col & 63)) & 1) << k);
      }
    }
  }
}
