// Native per-cell actor engine — C++ twin of runtime/actor_engine.py.
//
// Implements the reference's compute-layer protocol (CellActor.scala +
// NextStateCellGathererActor.scala, see SURVEY.md §2-§3) as a deterministic
// FIFO event loop over per-cell actors:
//   - epoch-keyed state history seeded {0: initial} (CellActor.scala:34)
//   - lazy advance gated by a waiting latch (CellActor.scala:41-47)
//   - per-step gatherer asking all 8 Moore neighbors
//     (NextStateCellGathererActor.scala:32-36)
//   - requests for not-yet-computed epochs queue and flush on set
//     (CellActor.scala:71-77, 82-88)
//   - crash -> history reset to epoch 0, replay forward out of neighbor
//     histories (SURVEY.md §3.3)
//   - tile mode: out-of-bounds neighbors are ghost cells fed per-epoch from
//     the cluster halo (the remote cells' served history).
//
// The rule is data: birth/survive bitmasks + state count (Generations decay),
// exactly as in ops/rules.py.  Exposed as a C ABI for ctypes; no Python.h
// dependency so it builds with a bare `g++ -shared -fPIC`.

#include <cstdint>
#include <cstring>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

struct Cell {
  std::unordered_map<int32_t, uint8_t> history;
  std::unordered_map<int32_t, std::vector<int64_t>> queued;  // epoch -> gids
  uint8_t initial = 0;
  bool waiting = false;
  bool is_ghost = false;  // ghosts serve history only; they never step
  int32_t epoch = 0;      // max key of history (tracked incrementally)
};

struct Gatherer {
  int32_t cell_index;  // owner cell (flat index)
  int32_t epoch;       // gathering neighbor states AT this epoch
  uint8_t current_state;
  int32_t pending;                 // distinct neighbors still unanswered
  std::vector<int32_t> neighbors;  // flat indices, with multiplicity
  std::vector<uint8_t> states;     // per-neighbor replies (by slot)
  std::vector<uint8_t> answered;   // per-slot flag
};

enum MsgKind : uint8_t {
  MSG_CURRENT_EPOCH,
  MSG_GET_TO_NEXT,
  MSG_GET_STATE,
  MSG_STATE_REPLY,
  MSG_SET_STATE,
};

struct Msg {
  MsgKind kind;
  int32_t a;  // cell index (or requestee index for GET_STATE)
  int64_t b;  // gatherer id
  int32_t c;  // epoch (GET_STATE/SET_STATE) or neighbor slot (STATE_REPLY)
  uint8_t d;  // state payload
};

struct Board {
  int32_t h = 0, w = 0;            // interior shape
  int32_t fh = 0, fw = 0;          // full shape incl. ghost ring (tile mode)
  bool tile_mode = false;          // ghosts vs torus
  uint32_t birth_mask = 0, survive_mask = 0;
  int32_t states = 2;
  int32_t kind = 0;  // 0 totalistic, 1 wireworld (ops/rules.py Rule.kind)
  int32_t global_epoch = 0;
  int64_t next_gid = 0;
  int64_t messages = 0;
  std::vector<Cell> cells;  // fh*fw entries (== h*w when not tiled)
  std::unordered_map<int64_t, Gatherer> gatherers;
  // neighbor slot table: per interior cell, 8 flat indices
  std::vector<int32_t> nbr;
  std::deque<Msg> mailbox;

  int32_t flat(int32_t y, int32_t x) const {
    if (tile_mode) return (y + 1) * fw + (x + 1);  // ghost ring offset
    return y * fw + x;
  }
  bool ghost(int32_t idx) const { return cells[idx].is_ghost; }
};

void build_neighbors(Board& b) {
  b.nbr.assign(static_cast<size_t>(b.h) * b.w * 8, 0);
  for (int32_t y = 0; y < b.h; ++y) {
    for (int32_t x = 0; x < b.w; ++x) {
      int32_t* out = &b.nbr[(static_cast<size_t>(y) * b.w + x) * 8];
      int k = 0;
      for (int32_t dy = -1; dy <= 1; ++dy) {
        for (int32_t dx = -1; dx <= 1; ++dx) {
          if (dy == 0 && dx == 0) continue;
          int32_t ny = y + dy, nx = x + dx;
          if (!b.tile_mode) {
            ny = (ny + b.h) % b.h;
            nx = (nx + b.w) % b.w;
          }
          out[k++] = b.flat(ny, nx);
        }
      }
    }
  }
}

uint8_t apply_rule(const Board& b, uint8_t current, int32_t alive) {
  if (b.kind == 1) {
    // Wireworld: head -> tail, tail -> conductor, conductor -> head iff the
    // head count hits the birth mask, empty stays (ops/stencil.apply_rule).
    if (current == 1) return 2;
    if (current == 2) return 3;
    if (current == 3 && ((b.birth_mask >> alive) & 1u)) return 1;
    return current;
  }
  if (b.states == 2) {
    uint32_t mask = current == 1 ? b.survive_mask : b.birth_mask;
    return static_cast<uint8_t>((mask >> alive) & 1u);
  }
  // Generations CA: dead -> birth?, alive -> survive? else decay, refractory
  // states count down to dead (ops/rules.py semantics).
  if (current == 0) return static_cast<uint8_t>((b.birth_mask >> alive) & 1u);
  if (current == 1) {
    if ((b.survive_mask >> alive) & 1u) return 1;
    return static_cast<uint8_t>(2 % b.states);
  }
  return static_cast<uint8_t>((current + 1) % b.states);
}

void set_history(Cell& c, int32_t epoch, uint8_t state) {
  c.history[epoch] = state;
  if (epoch > c.epoch) c.epoch = epoch;
}

void drain(Board& b) {
  while (!b.mailbox.empty()) {
    Msg m = b.mailbox.front();
    b.mailbox.pop_front();
    ++b.messages;
    switch (m.kind) {
      case MSG_CURRENT_EPOCH: {
        Cell& c = b.cells[m.a];
        if (!c.is_ghost && c.epoch < b.global_epoch && !c.waiting) {
          c.waiting = true;
          b.mailbox.push_back({MSG_GET_TO_NEXT, m.a, 0, 0, 0});
        }
        break;
      }
      case MSG_GET_TO_NEXT: {
        Cell& c = b.cells[m.a];
        int64_t gid = b.next_gid++;
        Gatherer g;
        g.cell_index = m.a;
        g.epoch = c.epoch;
        g.current_state = c.history[c.epoch];
        // interior slot table lookup needs interior coords
        int32_t iy, ix;
        if (b.tile_mode) {
          iy = m.a / b.fw - 1;
          ix = m.a % b.fw - 1;
        } else {
          iy = m.a / b.fw;
          ix = m.a % b.fw;
        }
        const int32_t* nb = &b.nbr[(static_cast<size_t>(iy) * b.w + ix) * 8];
        g.neighbors.assign(nb, nb + 8);
        g.states.assign(8, 0);
        g.answered.assign(8, 0);
        // Distinct-target asks (GatheredData set semantics): one GET_STATE
        // per distinct neighbor; the reply fills every slot of that target.
        int32_t distinct = 0;
        for (int s = 0; s < 8; ++s) {
          bool first = true;
          for (int t = 0; t < s; ++t)
            if (g.neighbors[t] == g.neighbors[s]) { first = false; break; }
          if (first) {
            ++distinct;
            b.mailbox.push_back({MSG_GET_STATE, g.neighbors[s], gid, g.epoch, 0});
          }
        }
        g.pending = distinct;
        b.gatherers.emplace(gid, std::move(g));
        break;
      }
      case MSG_GET_STATE: {
        Cell& c = b.cells[m.a];
        auto it = c.history.find(m.c);
        if (it != c.history.end()) {
          b.mailbox.push_back({MSG_STATE_REPLY, m.a, m.b, 0, it->second});
        } else {
          c.queued[m.c].push_back(m.b);
        }
        break;
      }
      case MSG_STATE_REPLY: {
        auto git = b.gatherers.find(m.b);
        if (git == b.gatherers.end()) break;
        Gatherer& g = git->second;
        bool newly = false;
        for (int s = 0; s < 8; ++s) {
          if (g.neighbors[s] == m.a && !g.answered[s]) {
            g.answered[s] = 1;
            g.states[s] = m.d;
            newly = true;
          }
        }
        if (newly && --g.pending == 0) {
          int32_t alive = 0;
          for (int s = 0; s < 8; ++s) alive += g.states[s] == 1;
          uint8_t next = apply_rule(b, g.current_state, alive);
          Msg set{MSG_SET_STATE, g.cell_index, 0, g.epoch + 1, next};
          b.gatherers.erase(git);
          b.mailbox.push_back(set);
        }
        break;
      }
      case MSG_SET_STATE: {
        Cell& c = b.cells[m.a];
        // guard: previous epoch must exist (CellActor.scala:29-30,79)
        if (c.history.find(m.c - 1) == c.history.end()) break;
        set_history(c, m.c, m.d);
        c.waiting = false;
        auto q = c.queued.find(m.c);
        if (q != c.queued.end()) {
          for (int64_t gid : q->second)
            b.mailbox.push_back({MSG_STATE_REPLY, m.a, gid, 0, m.d});
          c.queued.erase(q);
        }
        b.mailbox.push_back({MSG_CURRENT_EPOCH, m.a, 0, 0, 0});
        break;
      }
    }
  }
}

}  // namespace

extern "C" {

void* ae_create(int32_t h, int32_t w, const uint8_t* board,
                uint32_t birth_mask, uint32_t survive_mask, int32_t states,
                int32_t tile_mode, int32_t kind) {
  // Flat cell indices are int32 throughout (Msg.a, nbr table); reject boards
  // whose (ghost-ring-padded) index space would overflow.  The per-cell
  // engine is the small-board parity path, so this is not a real limit.
  if (h <= 0 || w <= 0) return nullptr;
  int64_t fh = static_cast<int64_t>(h) + (tile_mode ? 2 : 0);
  int64_t fw = static_cast<int64_t>(w) + (tile_mode ? 2 : 0);
  if (fh * fw > INT32_MAX) return nullptr;
  Board* b = new Board();
  b->h = h;
  b->w = w;
  b->tile_mode = tile_mode != 0;
  b->fh = tile_mode ? h + 2 : h;
  b->fw = tile_mode ? w + 2 : w;
  b->birth_mask = birth_mask;
  b->survive_mask = survive_mask;
  b->states = states;
  b->kind = kind;
  b->cells.assign(static_cast<size_t>(b->fh) * b->fw, Cell());
  for (int32_t y = 0; y < b->fh; ++y) {
    for (int32_t x = 0; x < b->fw; ++x) {
      Cell& c = b->cells[static_cast<size_t>(y) * b->fw + x];
      if (tile_mode && (y == 0 || x == 0 || y == b->fh - 1 || x == b->fw - 1)) {
        c.is_ghost = true;  // no history until a halo feeds it
      } else {
        int32_t iy = tile_mode ? y - 1 : y;
        int32_t ix = tile_mode ? x - 1 : x;
        c.initial = board[static_cast<size_t>(iy) * w + ix];
        set_history(c, 0, c.initial);
      }
    }
  }
  build_neighbors(*b);
  return b;
}

void ae_destroy(void* p) { delete static_cast<Board*>(p); }

void ae_advance_to(void* p, int32_t target) {
  Board* b = static_cast<Board*>(p);
  if (target > b->global_epoch) b->global_epoch = target;
  for (size_t i = 0; i < b->cells.size(); ++i)
    if (!b->cells[i].is_ghost)
      b->mailbox.push_back({MSG_CURRENT_EPOCH, static_cast<int32_t>(i), 0, 0, 0});
  drain(*b);
}

void ae_crash_cell(void* p, int32_t y, int32_t x) {
  Board* b = static_cast<Board*>(p);
  Cell& c = b->cells[b->flat(y, x)];
  c.history.clear();
  c.queued.clear();
  c.epoch = 0;
  c.waiting = false;
  set_history(c, 0, c.initial);
  b->mailbox.push_back({MSG_CURRENT_EPOCH, b->flat(y, x), 0, 0, 0});
  drain(*b);
}

void ae_feed_halo(void* p, int32_t epoch, const uint8_t* padded) {
  // padded is (h+2, w+2) row-major; ghosts take their ring value at `epoch`.
  Board* b = static_cast<Board*>(p);
  for (int32_t y = 0; y < b->fh; ++y) {
    for (int32_t x = 0; x < b->fw; ++x) {
      Cell& c = b->cells[static_cast<size_t>(y) * b->fw + x];
      if (!c.is_ghost) continue;
      uint8_t state = padded[static_cast<size_t>(y) * b->fw + x];
      set_history(c, epoch, state);
      auto q = c.queued.find(epoch);
      if (q != c.queued.end()) {
        for (int64_t gid : q->second)
          b->mailbox.push_back(
              {MSG_STATE_REPLY, static_cast<int32_t>(y * b->fw + x), gid, 0, state});
        c.queued.erase(q);
      }
    }
  }
  drain(*b);
}

void ae_get_board(void* p, uint8_t* out) {
  Board* b = static_cast<Board*>(p);
  for (int32_t y = 0; y < b->h; ++y)
    for (int32_t x = 0; x < b->w; ++x) {
      const Cell& c = b->cells[b->flat(y, x)];
      out[static_cast<size_t>(y) * b->w + x] = c.history.at(c.epoch);
    }
}

int32_t ae_min_epoch(void* p) {
  Board* b = static_cast<Board*>(p);
  int32_t m = INT32_MAX;
  for (const Cell& c : b->cells)
    if (!c.is_ghost && c.epoch < m) m = c.epoch;
  return m == INT32_MAX ? 0 : m;
}

int64_t ae_messages(void* p) { return static_cast<Board*>(p)->messages; }

void ae_prune_below(void* p, int32_t epoch) {
  Board* b = static_cast<Board*>(p);
  for (Cell& c : b->cells) {
    // The top-of-history entry (c.epoch) is always kept, so a non-empty
    // history stays non-empty.
    for (auto it = c.history.begin(); it != c.history.end();) {
      if (it->first < epoch && it->first != c.epoch)
        it = c.history.erase(it);
      else
        ++it;
    }
  }
}

}  // extern "C"
